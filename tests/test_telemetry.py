"""Telemetry plane (ISSUE 6): registry export golden-texts, nested
span parentage, Chrome-trace rendering, device-time attribution, and
the perf-regression gate.

Everything here is host-plane and device-free except nothing — the
telemetry plane's whole design constraint is that it never touches
jitted code (the ``engine_step_telemetry`` lint entry pins that side;
tests/test_serving_faults.py covers the serving integration). Fake
clocks make every duration assertion exact.
"""

import json
import math
import urllib.request

import pytest

from akka_allreduce_tpu.runtime.tracing import Tracer
from akka_allreduce_tpu.telemetry import (
    DeviceTimer,
    Histogram,
    MetricsRegistry,
    chrome_trace,
    parse_prometheus_text,
)
from akka_allreduce_tpu.telemetry.regression import (
    GateReport,
    default_gated,
    gate_section,
    load_banked,
    run_gate,
)


class TestHistogram:
    def test_percentiles_nearest_rank(self):
        h = Histogram()
        for v in (5, 1, 3, 2, 4):
            h.record(v)
        assert h.percentile(50) == 3
        assert h.percentile(90) == 5
        assert h.percentile(0) == 1
        assert h.count == 5 and h.mean == 3

    def test_sort_cache_invalidated_by_record(self):
        """The satellite fix: the sort is cached between records (one
        sort serves a whole summary), and a new record invalidates it —
        stale-cache percentiles would be silently wrong."""
        h = Histogram()
        h.record(10.0)
        assert h.percentile(50) == 10.0
        h.record(1.0)  # must invalidate the cached sort
        assert h.percentile(50) == 1.0
        assert h.percentile(99) == 10.0
        # summary shares one sort and agrees with percentile()
        s = h.summary()
        assert s["p50"] == 1.0 and s["max"] == 10.0 and s["count"] == 2

    def test_merge_aggregates_replicas(self):
        a, b = Histogram(), Histogram()
        for v in (1, 2):
            a.record(v)
        for v in (3, 4):
            b.record(v)
        assert a.merge(b) is a
        assert a.count == 4 and a.percentile(100) == 4
        assert b.count == 2  # other unchanged
        # merge after a cached sort still invalidates
        assert a.percentile(50) == 2

    def test_empty(self):
        h = Histogram()
        assert h.percentile(50) is None
        assert h.summary() == {"count": 0}


class TestRegistry:
    def test_prometheus_text_golden(self):
        r = MetricsRegistry()
        c = r.counter("reqs_total", help="requests")
        c.inc()
        c.inc(2)
        g = r.gauge("occupancy")
        g.set(0.25)
        h = r.histogram("lat_seconds")
        for v in (0.1, 0.2, 0.4, 0.8):
            h.record(v)
        text = r.to_prometheus_text()
        assert "# HELP reqs_total requests" in text
        assert "# TYPE reqs_total counter" in text
        assert "\nreqs_total 3\n" in text
        assert "occupancy 0.25" in text
        assert "# TYPE lat_seconds summary" in text
        assert 'lat_seconds{quantile="0.5"} 0.2' in text
        assert 'lat_seconds{quantile="0.99"} 0.8' in text
        assert "lat_seconds_count 4" in text

    def test_parse_round_trip(self):
        r = MetricsRegistry()
        r.counter("a_total", labels={"reason": "eos"}).inc(7)
        r.counter("a_total", labels={"reason": "stop"}).inc(2)
        p = parse_prometheus_text(r.to_prometheus_text())
        assert p[("a_total", (("reason", "eos"),))] == 7
        assert p[("a_total", (("reason", "stop"),))] == 2

    def test_callbacks_pull_live_state(self):
        state = {"n": 0}
        r = MetricsRegistry()
        r.register_callback("live_total", lambda: state["n"])
        assert r.value("live_total") == 0
        state["n"] = 5
        assert parse_prometheus_text(r.to_prometheus_text())[
            ("live_total", ())] == 5

    def test_owned_series_get_or_create_callbacks_strict(self):
        """A restarted component (the drain/recovery choreography)
        re-registers its owned series and must get the SAME cell; two
        pull callbacks under one name stay an error (aliasing)."""
        r = MetricsRegistry()
        c1 = r.counter("x_total")
        c1.inc()
        c2 = r.counter("x_total")
        assert c2 is c1
        r.register_callback("cb_total", lambda: 1)
        with pytest.raises(ValueError, match="already registered"):
            r.register_callback("cb_total", lambda: 2)
        with pytest.raises(ValueError, match="already registered"):
            r.counter("cb_total")  # owned over a callback: still wrong

    def test_kind_conflict_rejected(self):
        r = MetricsRegistry()
        r.counter("m")
        with pytest.raises(ValueError, match="already registered as"):
            r.gauge("m", labels={"x": "1"})

    def test_json_export(self):
        r = MetricsRegistry()
        r.counter("n_total").inc(4)
        r.histogram("h").record(1.5)
        doc = json.loads(json.dumps(r.to_json()))
        assert doc["n_total"]["values"][0]["value"] == 4
        assert doc["h"]["values"][0]["p50"] == 1.5

    def test_snapshot_write_and_http(self, tmp_path):
        r = MetricsRegistry()
        r.counter("snap_total").inc(9)
        path = tmp_path / "m.prom"
        r.write_snapshot(str(path))
        assert parse_prometheus_text(path.read_text())[
            ("snap_total", ())] == 9
        with r.serve_http(port=0) as srv:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=10).read().decode()
            assert parse_prometheus_text(body)[("snap_total", ())] == 9
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics.json",
                timeout=10).read().decode())
            assert doc["snap_total"]["values"][0]["value"] == 9


class TestTracerSpans:
    def test_nested_parentage(self):
        t = Tracer()
        with t.span("outer") as outer_id:
            t.record("point", rid=1)
            with t.span("inner") as inner_id:
                assert t.current_span_id == inner_id
        assert t.current_span_id is None
        by_kind = {e.kind: e for e in t.events}
        assert by_kind["outer"].span_id == outer_id
        assert by_kind["outer"].parent_id is None
        assert by_kind["inner"].parent_id == outer_id
        assert by_kind["point"].parent_id == outer_id
        assert inner_id != outer_id

    def test_background_thread_events_not_misparented(self):
        """The span stack is per-thread: a background recorder (the
        host sampler) must not have its events parented to whatever
        span the main thread happens to have open — cross-thread
        nesting would be a lie about structure."""
        import threading
        t = Tracer()
        done = threading.Event()
        go = threading.Event()

        def sampler():
            go.wait(5)
            t.record("host_resources", rss_mb=1.0)
            done.set()

        th = threading.Thread(target=sampler)
        th.start()
        with t.span("serve_step"):
            go.set()
            assert done.wait(5)
        th.join(5)
        ev = next(e for e in t.events if e.kind == "host_resources")
        assert ev.parent_id is None

    def test_record_span_post_hoc(self):
        t = Tracer()
        with t.span("outer") as outer_id:
            ev = t.record_span("timed", ts=1.0, duration_s=0.5, x=3)
        assert ev.parent_id == outer_id
        assert ev.duration_s == 0.5 and ev.fields == {"x": 3}

    def test_jsonl_round_trip_carries_ids(self, tmp_path):
        t = Tracer()
        with t.span("a"):
            t.record("b")
        path = tmp_path / "t.jsonl"
        t.write_jsonl(str(path))
        rows = Tracer.read_jsonl(str(path))
        a = next(r for r in rows if r["kind"] == "a")
        b = next(r for r in rows if r["kind"] == "b")
        assert a["span_id"] == b["parent_id"]
        assert "duration_s" in a


class TestChromeTrace:
    def _lifecycle_tracer(self):
        clock = iter(float(i) for i in range(100))
        t = Tracer(clock=lambda: next(clock))
        t.record("serve_submit", rid=0)
        t.record("serve_admit", rid=0, slot=1)
        with t.span("serve_step", occupied=1):
            pass
        t.record("serve_failure", rid=0, reason="nan")
        t.record("serve_admit", rid=0, slot=0)  # the retry's admit
        t.record("serve_complete", rid=0, tokens=4)
        return t

    def test_loadable_and_nested(self, tmp_path):
        t = self._lifecycle_tracer()
        path = tmp_path / "trace.json"
        n = t.write_chrome_trace(str(path))
        doc = json.loads(path.read_text())  # Perfetto-loadable JSON
        assert len(doc["traceEvents"]) == n
        req = [e for e in doc["traceEvents"] if e["name"] == "request"]
        assert len(req) == 1
        # every synthesized child nests inside the request slice
        for e in doc["traceEvents"]:
            if e["name"] in ("queued", "decode"):
                assert e["tid"] == req[0]["tid"]
                assert e["ts"] >= req[0]["ts"]
                assert e["ts"] + e["dur"] <= \
                    req[0]["ts"] + req[0]["dur"] + 1e-9

    def test_correlation_survives_retry(self):
        """One rid, a failure, a retried admit: the request track holds
        TWO queued/decode pairs inside one request span — the retry is
        visible as structure, not lost correlation."""
        doc = chrome_trace(self._lifecycle_tracer().events)
        names = [e["name"] for e in doc["traceEvents"]
                 if e.get("tid", 0) >= 1000 and e["ph"] == "X"]
        assert names.count("queued") == 2
        assert names.count("decode") == 2
        assert names.count("request") == 1

    def test_span_ids_ride_args_and_tracks_split(self):
        t = Tracer()
        with t.span("outer"):
            with t.span("inner"):
                pass
        doc = chrome_trace(t.events)
        inner = next(e for e in doc["traceEvents"]
                     if e["name"] == "inner")
        outer = next(e for e in doc["traceEvents"]
                     if e["name"] == "outer")
        assert inner["args"]["parent_id"] == outer["args"]["span_id"]
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["name"] == "thread_name"}
        assert "engine" in names


class TestDeviceTimer:
    def test_host_device_gap_split_exact(self):
        clock = iter([
            10.0,   # span 1 enter
            10.1,   # mark_dispatched
            10.5,   # span 1 exit (device = 0.4s)
            11.0,   # span 2 enter (gap = 0.5s)
            11.2,   # mark
            11.3,   # exit
        ])
        reg = MetricsRegistry()
        t = DeviceTimer("engine", registry=reg, annotate=False,
                        clock=lambda: next(clock))
        with t.span() as s:
            s.mark_dispatched()
        with t.span() as s:
            s.mark_dispatched()
        assert t.host_ms._vals == pytest.approx([100.0, 200.0])
        assert t.device_ms._vals == pytest.approx([400.0, 100.0])
        assert t.gap_ms._vals == pytest.approx([500.0])
        # the series are ON the registry under the documented names
        assert math.isclose(
            reg.value("engine_dispatch_gap_ms").percentile(50), 500.0)

    def test_unmarked_span_charges_host(self):
        clock = iter([1.0, 2.0])
        t = DeviceTimer("x", annotate=False, clock=lambda: next(clock))
        with t.span():
            pass
        assert t.host_ms._vals == [1000.0]
        assert t.device_ms._vals == [0.0]

    def test_failed_dispatch_records_nothing(self):
        """A dispatch that raises (watchdog trip, injected fault) must
        not land in the device-time series — a watchdog timeout in the
        host_ms tail would be exactly the misattribution the series
        exists to prevent, and the span-count == dispatch-count
        invariant (serve --selfcheck) must survive faulted runs."""
        tracer = Tracer()
        # reads: span-1 enter; span-2 enter, mark, exit (the failed
        # span's exit path reads no clock — that is the point)
        clock = iter([1.0, 10.0, 10.1, 10.3])
        t = DeviceTimer("engine", tracer=tracer, annotate=False,
                        clock=lambda: next(clock))
        with pytest.raises(RuntimeError):
            with t.span():
                raise RuntimeError("hung dispatch")
        assert t.host_ms.count == 0 and t.device_ms.count == 0
        assert tracer.events == []
        # the next (successful) span starts gap-free: the recovery
        # interval is not a scheduling bubble
        with t.span() as s:
            s.mark_dispatched()
        assert t.gap_ms._vals == []
        assert t.host_ms._vals == pytest.approx([100.0])
        assert t.device_ms._vals == pytest.approx([200.0])

    def test_reset_gap_skips_recovery_interval(self):
        clock = iter([1.0, 2.0, 10.0, 11.0])
        t = DeviceTimer("x", annotate=False, clock=lambda: next(clock))
        with t.span():
            pass
        t.reset_gap()  # e.g. watchdog recovery in between
        with t.span():
            pass
        assert t.gap_ms._vals == []

    def test_dispatch_site_annotation(self):
        """annotate_site='dispatch' (the engine's configuration): the
        span itself opens no annotation; DeviceSpan.annotation() hands
        the dispatch callable a context manager to open on WHATEVER
        thread runs the dispatch (profiler annotations are
        thread-local — the watchdog executor is the point)."""
        with pytest.raises(ValueError, match="annotate_site"):
            DeviceTimer("x", annotate_site="nope")
        clock = iter([1.0, 1.2, 1.5])
        t = DeviceTimer("x", annotate_site="dispatch",
                        clock=lambda: next(clock))
        with t.span() as s:
            with s.annotation():  # the dispatch thread's bracket
                s.mark_dispatched()
        assert t.host_ms._vals == pytest.approx([200.0])
        # annotation() is null when annotation is off entirely
        t2 = DeviceTimer("y", annotate=False, annotate_site="dispatch",
                         clock=iter([0.0, 0.1]).__next__)
        with t2.span() as s2:
            with s2.annotation():
                pass

    def test_tracer_span_recorded(self):
        tracer = Tracer()
        clock = iter([1.0, 1.5])
        t = DeviceTimer("engine", tracer=tracer, annotate=False,
                        clock=lambda: next(clock))
        with t.span(occupied=3):
            pass
        (ev,) = tracer.events
        assert ev.kind == "engine_dispatch"
        assert ev.duration_s == pytest.approx(0.5)
        assert ev.fields["occupied"] == 3
        assert "host_ms" in ev.fields and "device_ms" in ev.fields


class TestServingMetricsOnRegistry:
    def test_prometheus_agrees_with_summary(self):
        from akka_allreduce_tpu.serving import ServingMetrics
        clock = iter(float(i) for i in range(100))
        m = ServingMetrics(clock=lambda: next(clock))
        for rid in range(3):
            m.on_submit(rid)
            m.on_admit(rid, slot=rid, prompt_len=4)
            m.on_block_tokens(rid, submitted_at=0.0, n=2)
            m.on_complete(rid, n_tokens=5, reason="eos")
        m.on_retry(1)
        m.observe(queue_depth=2, occupancy=0.5)
        summ = m.summary()
        prom = parse_prometheus_text(m.registry.to_prometheus_text())
        assert prom[("serve_completed_total", ())] \
            == summ["requests"]["completed"] == 3
        assert prom[("serve_submitted_total", ())] == 3
        assert prom[("serve_retries_total", ())] \
            == summ["faults"]["retries_total"] == 1
        assert prom[("serve_decode_tokens_total", ())] \
            == summ["tokens"]["decode"] == 6
        # TTFT: prom exports seconds; the summary renders ms — same
        # cells, exact agreement through the unit conversion
        for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            got = prom[("serve_ttft_seconds", (("quantile", q),))]
            assert round(got * 1e3, 3) == summ["ttft_ms"][key]
        assert prom[("serve_ttft_seconds_count", ())] \
            == summ["ttft_ms"]["count"]

    def test_drain_persisted_counter(self):
        from akka_allreduce_tpu.serving import ServingMetrics
        m = ServingMetrics()
        m.on_drain_persisted(2)
        assert m.registry.value("serve_drain_persisted_total") == 2

    def test_shared_registry_rejects_second_metrics(self):
        """Two ServingMetrics on ONE registry would alias every
        serve_* series — the registry refuses (each engine replica
        gets its own registry; aggregation is Histogram.merge's job)."""
        from akka_allreduce_tpu.serving import ServingMetrics
        m = ServingMetrics()
        with pytest.raises(ValueError, match="already registered"):
            ServingMetrics(registry=m.registry)


BANKED = {
    "serving_sequential_tok_s_cpu": [159.3],
    "serving_engine_s4_tok_s_cpu": [307.7],
    "serving_throughput_speedup_s4": [1.932, 1.8],  # re-capture: median
}


def rows(**kv):
    return [{"metric": k, "value": v} for k, v in kv.items()]


class TestRegressionGate:
    def test_default_gated_is_the_claim_rows(self):
        assert default_gated("serving_throughput_speedup_s4")
        assert default_gated("multi_step_decode_best")
        assert not default_gated("serving_engine_s4_tok_s_cpu")
        assert not default_gated("allreduce_goodput_25M_f32_1cpu")

    def test_passes_on_banked_equal_rows(self):
        res = gate_section("serving_throughput", BANKED, rows(
            serving_sequential_tok_s_cpu=159.3,
            serving_engine_s4_tok_s_cpu=307.7,
            serving_throughput_speedup_s4=1.866))
        gated = [r for r in res if r.ok is not None]
        assert len(gated) == 1 and gated[0].ok
        assert gated[0].banked_median == pytest.approx(1.866)  # median

    def test_fails_on_2x_regression(self):
        res = gate_section("serving_throughput", BANKED, rows(
            serving_throughput_speedup_s4=1.866 / 2))
        bad = [r for r in res if r.ok is False]
        assert len(bad) == 1
        assert bad[0].metric == "serving_throughput_speedup_s4"
        assert "regressed" in bad[0].note

    def test_within_tolerance_passes(self):
        # the banked capture's own recorded repeat-run swing must pass
        res = gate_section("serving_throughput", BANKED, rows(
            serving_throughput_speedup_s4=1.63))
        assert all(r.ok is not False for r in res)

    def test_missing_gated_fresh_row_fails(self):
        res = gate_section("serving_throughput", BANKED, [])
        bad = {r.metric for r in res if r.ok is False}
        assert bad == {"serving_throughput_speedup_s4"}

    def test_error_row_fails_gated_metric(self):
        res = gate_section("serving_throughput", BANKED, [
            {"metric": "serving_throughput_speedup_s4", "value": 0.0,
             "error": "OOM"}])
        (bad,) = [r for r in res if r.ok is False]
        assert "OOM" in bad.note

    def test_gate_all_gates_value_rows(self):
        res = gate_section("serving_throughput", BANKED, rows(
            serving_sequential_tok_s_cpu=10.0,
            serving_engine_s4_tok_s_cpu=307.7,
            serving_throughput_speedup_s4=1.9), gate_all=True)
        assert any(r.metric == "serving_sequential_tok_s_cpu"
                   and r.ok is False for r in res)

    def test_tolerance_validation(self):
        with pytest.raises(ValueError, match="tolerance"):
            gate_section("s", BANKED, [], tolerance=1.5)
        # the hard cap: at tol 0.5 an exact 2x regression would PASS
        # the >= comparison — the acceptance property forbids it
        with pytest.raises(ValueError, match="2x"):
            gate_section("s", BANKED, [], tolerance=0.5)

    def test_exact_2x_regression_fails_every_section(self):
        """The acceptance case at the boundary: fresh == median/2 must
        fail under every section's DEFAULT tolerance (all < 0.5)."""
        from akka_allreduce_tpu.telemetry.regression import (
            SECTION_TOLERANCE)
        for section, tol in SECTION_TOLERANCE.items():
            assert tol < 0.5, section
            res = gate_section(section,
                               {"x_speedup_s4": [2.0]},
                               rows(x_speedup_s4=1.0))
            (gated,) = [r for r in res if r.ok is not None]
            assert gated.ok is False, section

    def test_load_banked_reads_the_repo_bank(self):
        import os
        bank = load_banked(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "perf_capture"))
        assert "serving_throughput" in bank
        assert "multi_step_decode" in bank
        assert bank["serving_throughput"][
            "serving_throughput_speedup_s4"]
        assert "multi_step_decode_best" in bank["multi_step_decode"]

    def test_run_gate_offline_pass_and_fail(self, tmp_path):
        cap = tmp_path / "caps"
        cap.mkdir()
        (cap / "serving.json").write_text(json.dumps({
            "section": "serving_throughput",
            "rows": [{"metric": "serving_throughput_speedup_s4",
                      "value": 2.0, "unit": "x"}]}))
        ok = run_gate(str(cap), sections=["serving_throughput"],
                      fresh_by_section={"serving_throughput": rows(
                          serving_throughput_speedup_s4=1.9)})
        assert isinstance(ok, GateReport) and ok.ok
        bad = run_gate(str(cap), sections=["serving_throughput"],
                       fresh_by_section={"serving_throughput": rows(
                           serving_throughput_speedup_s4=1.0)})
        assert not bad.ok
        assert bad.failed[0].metric == "serving_throughput_speedup_s4"
        doc = json.loads(json.dumps(bad.as_dict()))  # CI artifact shape
        assert doc["ok"] is False and doc["failed"]

    def test_zero_gated_rows_is_a_pass_not_a_red(self):
        """Banked rows with no claim metrics gate nothing: the verdict
        must be a (noted) pass — the text summary and the exit code
        read the same `ok`, so CI never sees a red log that says
        PASS."""
        banked = {"serving_sequential_tok_s_cpu": [100.0]}
        res = gate_section("serving_throughput", banked,
                           rows(serving_sequential_tok_s_cpu=10.0))
        rep = GateReport(sections={"serving_throughput": res},
                         skipped={}, tolerance=None)
        assert rep.ok and not rep.gated and not rep.failed

    def test_run_gate_skips_unbanked_sections(self, tmp_path):
        rep = run_gate(str(tmp_path), sections=["ab_overlap"],
                       fresh_by_section={"ab_overlap": []})
        assert rep.skipped and "ab_overlap" in rep.skipped
        # nothing gated anywhere + an explained skip is still a pass
        assert rep.ok

    def test_merge_best_takes_per_metric_max(self):
        from akka_allreduce_tpu.telemetry.regression import _merge_best
        merged = _merge_best(rows(a=1.0, b=5.0),
                             rows(a=2.0, b=3.0, c=7.0))
        assert {r["metric"]: r["value"] for r in merged} \
            == {"a": 2.0, "b": 5.0, "c": 7.0}
