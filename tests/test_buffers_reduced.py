"""Port of the reference's ReducedDataBuffer unit spec.

Scenario-for-scenario port of
reference: src/test/scala/sample/cluster/allreduce/buffer/ReducedDataBufferSpec.scala.
"""

import numpy as np
import pytest

from akka_allreduce_tpu.buffers import ReducedDataBuffer

rng = np.random.default_rng(1)


def random_floats(n):
    return rng.random(n, dtype=np.float32)


def test_even_blocks_story():
    """maxBlock=5, minBlock=5, peers=3, maxLag=4, threshold=0.7, chunk=2,
    total=15 — a single sequential story (the Scala WordSpec runs these
    clauses in order on one buffer)
    (reference: ReducedDataBufferSpec.scala:10-121)."""
    buf = ReducedDataBuffer(5, 5, 15, 3, 4, 0.7, 2)
    row = 1

    # "initialize buffers"
    assert buf.temporal_buffer.shape == (4, 3, 5)

    # "have zero counts"
    output, count = buf.get_with_counts(row)
    assert output.sum() == 0
    assert count.sum() == 0

    # "store first peer first chunk data"
    to_store = random_floats(2)
    buf.store(to_store, row, src_id=0, chunk_id=0, count=3)
    output, count = buf.get_with_counts(row)
    np.testing.assert_array_equal(output[:2], to_store)
    assert (count[:2] == 3).all()

    # "store last peer last chunk with smaller size"
    src = 2
    chunk = buf.num_chunks - 1
    with pytest.raises(IndexError):
        buf.store(random_floats(2), row, src, chunk, count=3)
    last_chunk_size = 5 - (buf.num_chunks - 1) * 2
    to_store = random_floats(last_chunk_size)
    buf.store(to_store, row, src, chunk, count=3)
    output, _ = buf.get_with_counts(row)
    np.testing.assert_array_equal(output[15 - last_chunk_size:], to_store)

    # "store until reach completion threshold":
    # gate = int(0.7 * 9 chunks) = 6 reduced chunks
    # (reference: ReducedDataBufferSpec.scala:72-92)
    assert buf.reach_completion_threshold(row) is False
    buf.store(random_floats(2), row, src_id=0, chunk_id=1, count=3)
    assert buf.reach_completion_threshold(row) is False
    buf.store(random_floats(2), row, src_id=1, chunk_id=0, count=3)
    buf.store(random_floats(2), row, src_id=1, chunk_id=1, count=3)
    assert buf.reach_completion_threshold(row) is False
    buf.store(random_floats(2), row, src_id=2, chunk_id=1, count=3)
    assert buf.reach_completion_threshold(row) is True

    # "get reduced row": peers 0 and 1 are missing their 3rd chunk; peer 2
    # its 1st (reference: ReducedDataBufferSpec.scala:95-119)
    reduced, counts = buf.get_with_counts(row)
    assert reduced.shape == counts.shape
    missing = [4, 9, 10, 11]
    for i in missing:
        assert reduced[i] == 0
        assert counts[i] == 0
    present = [i for i in range(15) if i not in missing]
    for i in present:
        assert counts[i] == 3


class TestUnevenBlocks:
    """maxBlock=6, minBlock=4, peers=3, threshold=1, chunk=2, total=16
    (reference: ReducedDataBufferSpec.scala:124-158)."""

    ROW = 1

    def test_store_until_completion_threshold(self):
        buf = ReducedDataBuffer(6, 4, 16, 3, 4, 1.0, 2)
        # total chunks = 3 + 3 + 2 = 8; gate = 8
        assert buf.reach_completion_threshold(self.ROW) is False
        for chunk_id in range(3):
            for peer_id in range(2):
                buf.store(random_floats(2), self.ROW, peer_id, chunk_id,
                          count=3)
                assert buf.reach_completion_threshold(self.ROW) is False
        buf.store(random_floats(2), self.ROW, 2, 0, count=3)
        assert buf.reach_completion_threshold(self.ROW) is False
        buf.store(random_floats(2), self.ROW, 2, 1, count=3)
        assert buf.reach_completion_threshold(self.ROW) is True

    def test_uneven_reassembly_counts(self):
        """Uneven last block: output slots past the last block's real extent
        stay zero-filled with zero counts."""
        buf = ReducedDataBuffer(6, 4, 16, 3, 4, 1.0, 2)
        for peer in range(3):
            block = 4 if peer == 2 else 6
            for chunk in range(buf.get_num_chunk(block)):
                size = min(2, block - 2 * chunk)
                buf.store(np.full(size, peer + 1, dtype=np.float32),
                          self.ROW, peer, chunk, count=peer + 1)
        out, counts = buf.get_with_counts(self.ROW)
        np.testing.assert_array_equal(out[:6], np.full(6, 1.0))
        np.testing.assert_array_equal(out[6:12], np.full(6, 2.0))
        np.testing.assert_array_equal(out[12:16], np.full(4, 3.0))
        assert (counts[:6] == 1).all()
        assert (counts[6:12] == 2).all()
        assert (counts[12:16] == 3).all()


class TestDegenerateGeometry:
    """Review findings: gates must stay attainable for geometries the
    reference crashes on but config.block_ranges supports."""

    def test_more_peers_than_elements_can_complete(self):
        # data_size=4, peers=8: blocks are 1,1,1,1,0,0,0,0 -> only 4
        # attainable chunks; gate must be 4, not 7.
        buf = ReducedDataBuffer(1, 0, 4, 8, 2, 1.0, 2)
        assert buf.total_chunks == 4
        assert buf.min_chunk_required == 4
        for peer in range(4):
            buf.store(np.ones(1, np.float32), 0, peer, 0, count=1)
        assert buf.reach_completion_threshold(0) is True

    def test_tiny_threshold_clamps_gate_to_one(self):
        # int(0.1 * 9) = 0 would deadlock; clamp to 1.
        buf = ReducedDataBuffer(5, 5, 15, 3, 4, 0.1, 2)
        assert buf.min_chunk_required == 1
        buf.store(np.ones(2, np.float32), 0, 0, 0, count=3)
        assert buf.reach_completion_threshold(0) is True

    def test_negative_src_id_raises(self):
        buf = ReducedDataBuffer(5, 5, 15, 3, 4, 0.7, 2)
        with pytest.raises(IndexError):
            buf.store(np.ones(2, np.float32), 0, -1, 0, count=3)
