"""In-memory fake of the JAX coordination-service KV client.

Implements exactly the surface KvRouter / DcnDeadlineTrainer use
(protocol/kv.py, runtime/dcn_train.py), with the real client's error
conventions: a missing key raises with ``NOT_FOUND`` in the message, a
non-overwritable set on an existing key raises with ``ALREADY_EXISTS``.
Thread-safe — protocol tests drive one fake from N trainer threads, the
in-process rendering of the reference's forged-peer TestKit harness
(reference: AllreduceSpec.scala; SURVEY.md §4).

``on_set`` is the fault-injection hook: called (key) BEFORE each write
lands, outside the lock, so a test can stall a publish mid-round (the
per-bucket contribution tests cut a worker between two bucket writes).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class FakeKvClient:
    def __init__(self,
                 on_set: Optional[Callable[[str], None]] = None):
        self._store: dict[str, object] = {}
        self._lock = threading.Lock()
        self.on_set = on_set

    # -- writes --------------------------------------------------------------

    def _set(self, key: str, value, allow_overwrite: bool) -> None:
        if self.on_set is not None:
            self.on_set(key)
        with self._lock:
            if not allow_overwrite and key in self._store:
                raise RuntimeError(f"ALREADY_EXISTS: key {key} is "
                                   f"already set")
            self._store[key] = value

    def key_value_set(self, key: str, value: str,
                      allow_overwrite: bool = False) -> None:
        self._set(key, str(value), allow_overwrite)

    def key_value_set_bytes(self, key: str, value: bytes,
                            allow_overwrite: bool = True) -> None:
        self._set(key, bytes(value), allow_overwrite)

    # -- reads ---------------------------------------------------------------

    def key_value_try_get(self, key: str) -> str:
        with self._lock:
            if key not in self._store:
                raise RuntimeError(f"NOT_FOUND: key {key}")
            return self._store[key]

    def key_value_try_get_bytes(self, key: str) -> bytes:
        return self.key_value_try_get(key)

    def _dir(self, prefix: str) -> list[tuple[str, object]]:
        with self._lock:
            out = [(k, v) for k, v in self._store.items()
                   if k.startswith(prefix)]
        if not out:
            raise RuntimeError(f"NOT_FOUND: no keys under {prefix}")
        return sorted(out)

    def key_value_dir_get(self, prefix: str) -> list[tuple[str, str]]:
        return self._dir(prefix)

    def key_value_dir_get_bytes(self,
                                prefix: str) -> list[tuple[str, bytes]]:
        return self._dir(prefix)

    # -- delete --------------------------------------------------------------

    def key_value_delete(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)
