"""Pins for the analytic ICI scaling model (parallel/scaling.py).

The model is the single-chip-honest rendering of BASELINE.md's 256-chip
north star; these tests pin its algebra (the claims are only auditable
if the formulas cannot drift) and the labeled-prediction framing.
"""

import numpy as np
import pytest

from akka_allreduce_tpu.parallel.scaling import (
    IciSpec,
    default_spec,
    format_table,
    predict,
    ring_wire_seconds,
    scaling_table,
)


class TestRingAlgebra:
    def test_wire_formula_exact(self):
        spec = IciSpec(link_gbytes_s=50.0, ring_directions=2, rings=1,
                       hop_latency_s=0.0)
        # n=4: 2(n-1)=6 steps of S/4 bytes at 100 GB/s
        s = ring_wire_seconds(400e6, 4, spec)
        assert s == pytest.approx(6 * 100e6 / 100e9)

    def test_single_chip_is_free(self):
        assert ring_wire_seconds(1e9, 1, IciSpec()) == 0.0

    def test_hop_latency_term(self):
        spec = IciSpec(link_gbytes_s=50.0, hop_latency_s=2e-6)
        base = IciSpec(link_gbytes_s=50.0, hop_latency_s=0.0)
        n = 8
        extra = (ring_wire_seconds(4e6, n, spec)
                 - ring_wire_seconds(4e6, n, base))
        assert extra == pytest.approx(2 * (n - 1) * 2e-6)

    def test_busbw_approaches_ring_ceiling_for_large_payload(self):
        """busbw -> ring bandwidth as the payload swamps latency and
        overhead — the property that makes 'efficiency' meaningful."""
        spec = IciSpec(link_gbytes_s=45.0)
        row = predict(4e12, 256, spec)  # 1T floats: latency negligible
        assert row.efficiency == pytest.approx(1.0, abs=1e-3)

    def test_overhead_floor_adds_not_maxes(self):
        spec = IciSpec()
        free = predict(400e6, 8, spec)
        floored = predict(400e6, 8, spec,
                          measured_1chip_goodput_gbps=305.0)
        assert floored.overhead_s == pytest.approx(400e6 / 305e9)
        assert floored.total_s == pytest.approx(
            free.total_s + floored.overhead_s)
        assert floored.efficiency < free.efficiency


class TestNorthStar:
    def test_256chip_100m_floats_above_80pct(self):
        """The BASELINE.md north-star row AS A PREDICTION: >= 80% ring
        efficiency at 256 chips on 100M f32, including this repo's
        measured 1-chip overhead floor. If a framework change drags the
        measured goodput low enough to break this, the model (and this
        pin) says so before any fleet does."""
        rows = scaling_table(100e6, chips=(256,),
                             measured_1chip_goodput_gbps=305.0)
        assert rows[0].efficiency >= 0.80

    def test_efficiency_erodes_with_chips_at_fixed_payload(self):
        effs = [r.efficiency for r in scaling_table(
            100e6, chips=(8, 64, 256),
            measured_1chip_goodput_gbps=305.0)]
        # the hop-latency term grows with n while moved bytes saturate
        assert effs[0] > effs[-1]

    def test_table_is_labeled_a_model(self):
        txt = format_table(scaling_table(100e6, chips=(8, 256)))
        assert "MODEL" in txt
        assert "256" in txt


class TestOverrides:
    def test_env_override_hits_default_spec_only(self, monkeypatch):
        monkeypatch.setenv("AATPU_ICI_GBPS", "90")
        assert default_spec().ring_gbytes_s == pytest.approx(180.0)
        # an EXPLICIT spec always means what it says: ambient env must
        # not silently rewrite an explicit argument
        assert IciSpec(link_gbytes_s=50.0).ring_gbytes_s == \
            pytest.approx(100.0)
        monkeypatch.delenv("AATPU_ICI_GBPS")
        assert default_spec().ring_gbytes_s == pytest.approx(90.0)

    @pytest.mark.parametrize("bad", ["0", "-3", "fast"])
    def test_env_garbage_fails_at_the_boundary(self, monkeypatch, bad):
        monkeypatch.setenv("AATPU_ICI_GBPS", bad)
        with pytest.raises(ValueError, match="AATPU_ICI_GBPS"):
            default_spec()

    def test_second_torus_ring_halves_wire_time(self):
        one = IciSpec(rings=1, hop_latency_s=0.0)
        two = IciSpec(rings=2, hop_latency_s=0.0)
        assert ring_wire_seconds(4e8, 16, two) == pytest.approx(
            ring_wire_seconds(4e8, 16, one) / 2)

    def test_moved_bytes_factor(self):
        """busbw / algobw == 2(n-1)/n exactly — the NCCL convention."""
        row = predict(4e8, 8, IciSpec(),
                      measured_1chip_goodput_gbps=300.0)
        assert row.busbw_gbytes_s / row.algobw_gbytes_s == pytest.approx(
            2 * 7 / 8)
        assert np.isfinite(row.total_s)
