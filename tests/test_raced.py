"""The dynamic lockset/happens-before detector (ISSUE 15,
runtime/raced.py): deliberately-racy fixture threads must produce
EXACT reports (field, both sites with file:line, both locksets), and
the happy paths — consistent locking, single-writer handoff over
``join``, RLock re-entry — must stay clean. The live integration pin
runs the real metrics registry under cross-thread scrape load."""

import threading

import pytest

from akka_allreduce_tpu.runtime import raced


class TwoLocks:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self.n = 0


class OneLock:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0


class Bare:
    def __init__(self):
        self.n = 0


def run_threads(*targets):
    ts = [threading.Thread(target=t, name=f"worker{i}")
          for i, t in enumerate(targets)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def interleave(*writers):
    """Run each writer once, in order, on its OWN thread, with every
    thread held alive until all have written — a deterministic
    observed interleaving (no reliance on GIL timeslice luck), which
    is exactly the evidence a lockset detector needs."""
    done = threading.Event()
    turns = [threading.Event() for _ in writers]

    def runner(i, fn):
        if i:
            turns[i - 1].wait(timeout=10)
        fn()
        turns[i].set()
        done.wait(timeout=10)   # stay alive: overlap is the point

    ts = [threading.Thread(target=runner, args=(i, fn),
                           name=f"worker{i}")
          for i, fn in enumerate(writers)]
    for t in ts:
        t.start()
    turns[-1].wait(timeout=10)
    done.set()
    for t in ts:
        t.join(timeout=10)


class TestWriteRaces:
    def test_disjoint_locksets_race_with_exact_report(self):
        with raced.trace(watch=(TwoLocks,)) as probe:
            obj = TwoLocks()

            def via_a():
                with obj._lock_a:
                    obj.n += 1

            def via_b():
                with obj._lock_b:
                    obj.n += 1

            interleave(via_a, via_b)
        report = probe.report()
        assert len(report.races) == 1
        race = report.races[0]
        assert race.field == "TwoLocks.n"
        # exact evidence: both sites name THIS file and a line, both
        # locksets name the disjoint locks
        assert "test_raced.py" in race.first_site
        assert "test_raced.py" in race.second_site
        assert all(s.rsplit(":", 1)[1].isdigit()
                   for s in (race.first_site, race.second_site))
        # lock names carry an instance token (C._lock#N) so reports
        # distinguish same-named locks on different instances
        held = sorted(ls[0].split("#")[0]
                      for ls in (race.first_lockset,
                                 race.second_lockset))
        assert held == ["TwoLocks._lock_a", "TwoLocks._lock_b"]
        with pytest.raises(AssertionError, match="TwoLocks.n"):
            report.assert_clean()

    def test_common_lock_is_clean(self):
        with raced.trace(watch=(OneLock,)) as probe:
            obj = OneLock()

            def w():
                for _ in range(30):
                    with obj._lock:
                        obj.n += 1

            run_threads(w, w, w)
        assert probe.report().clean
        assert probe.report().writes_seen > 60

    def test_no_locks_at_all_race(self):
        with raced.trace(watch=(Bare,)) as probe:
            obj = Bare()

            def w():
                obj.n += 1

            interleave(w, w)
        report = probe.report()
        assert len(report.races) == 1
        assert report.races[0].first_lockset == ()
        assert report.races[0].second_lockset == ()

    def test_partial_overlap_shrinks_candidate_to_race(self):
        # w1 holds {a,b}, w2 holds {b}: candidate {b} — clean so far;
        # then w3 holds {a}: {b} & {a} = {} — the lockset math's edge
        with raced.trace(watch=(TwoLocks,)) as probe:
            obj = TwoLocks()

            def both():
                with obj._lock_a, obj._lock_b:
                    obj.n += 1

            def only_b():
                with obj._lock_b:
                    obj.n += 1

            def only_a():
                with obj._lock_a:
                    obj.n += 1

            interleave(both, only_b, only_a)
        report = probe.report()
        assert len(report.races) == 1
        race = report.races[0]
        assert race.field == "TwoLocks.n"
        # the shrunken candidate {b} vs the final writer's {a}
        assert race.first_lockset[0].startswith("TwoLocks._lock_b")
        assert race.second_lockset[0].startswith("TwoLocks._lock_a")

    def test_wrong_instance_lock_is_a_race(self):
        # the classic wrong-instance-lock bug: both writers are
        # "locked", but each holds a DIFFERENT instance's lock — lock
        # identity (not the Class.attr name) must decide the
        # intersection
        with raced.trace(watch=(OneLock,)) as probe:
            shared = OneLock()
            decoy = OneLock()

            def via_own():
                with shared._lock:
                    shared.n += 1

            def via_decoy():
                with decoy._lock:    # BUG: wrong object's lock
                    shared.n += 1

            interleave(via_own, via_decoy)
        report = probe.report()
        assert len(report.races) == 1
        assert report.races[0].field == "OneLock.n"

    def test_sequential_thread_lifetimes_are_not_a_race(self):
        # the same disjoint-lockset writes, but each writer DIES
        # before the next starts: no observed overlap, no race — the
        # dead-owner handoff is the detector's join/HB rule
        with raced.trace(watch=(TwoLocks,)) as probe:
            obj = TwoLocks()

            def via(lk):
                with lk:
                    obj.n += 1

            run_threads(lambda: via(obj._lock_a))
            run_threads(lambda: via(obj._lock_b))
        assert probe.report().clean

    def test_join_handoff_is_not_a_race(self):
        with raced.trace(watch=(Bare,)) as probe:
            obj = Bare()

            def w():
                for _ in range(10):
                    obj.n += 1

            t = threading.Thread(target=w)
            t.start()
            t.join()
            obj.n = 99   # sequenced by the join: handoff, not a race
        assert probe.report().clean

    def test_constructor_writes_never_race_with_thread(self):
        # __init__ runs before Thread.start publishes the object —
        # the exclusive->shared ladder must not charge the ctor
        with raced.trace(watch=(Bare,)) as probe:
            obj = Bare()   # ctor writes n with no locks

            def w():
                for _ in range(10):
                    obj.n += 1

            t = threading.Thread(target=w)
            t.start()
            t.join()
        assert probe.report().clean


class TestInversions:
    def test_ab_ba_inversion_reported_without_deadlocking(self):
        with raced.trace(watch=(TwoLocks,)) as probe:
            obj = TwoLocks()

            def fwd():
                with obj._lock_a:
                    with obj._lock_b:
                        pass

            def rev():
                with obj._lock_b:
                    with obj._lock_a:
                        pass

            # sequential execution: the ORDER EDGES conflict even
            # though no actual deadlock can occur — exactly the bug
            # class that ships quiet and fires in production
            run_threads(fwd)
            run_threads(rev)
        report = probe.report()
        assert len(report.inversions) == 1
        inv = report.inversions[0]
        assert sorted(x.split("#")[0]
                      for x in (inv.lock_a, inv.lock_b)) == \
            ["TwoLocks._lock_a", "TwoLocks._lock_b"]
        assert "test_raced.py" in inv.ab_site
        assert "test_raced.py" in inv.ba_site
        with pytest.raises(AssertionError, match="INVERSION"):
            report.assert_clean()

    def test_consistent_order_is_clean(self):
        with raced.trace(watch=(TwoLocks,)) as probe:
            obj = TwoLocks()

            def fwd():
                with obj._lock_a:
                    with obj._lock_b:
                        pass

            run_threads(fwd, fwd)
        assert probe.report().clean

    def test_lock_churn_no_phantom_inversions(self):
        # freed locks' recycled addresses must not alias new locks:
        # every object acquires a then b (one consistent global
        # order), across many short-lived instances — zero inversions
        with raced.trace(watch=(TwoLocks,)) as probe:
            def wave():
                for _ in range(40):
                    obj = TwoLocks()
                    with obj._lock_a:
                        with obj._lock_b:
                            obj.n += 1

            run_threads(wave, wave)
        report = probe.report()
        assert report.inversions == []

    def test_rlock_reentry_no_false_edges(self):
        class WithRLock:
            def __init__(self):
                self._lock = threading.RLock()
                self.n = 0

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:   # re-entry, not a new acquisition
                    self.n += 1

        with raced.trace(watch=(WithRLock,)) as probe:
            obj = WithRLock()
            run_threads(obj.outer, obj.outer)
        assert probe.report().clean


class TestHarness:
    def test_trace_does_not_nest(self):
        with raced.trace(watch=(Bare,)):
            with pytest.raises(RuntimeError, match="nest"):
                with raced.trace(watch=(Bare,)):
                    pass

    def test_empty_watch_rejected(self):
        with pytest.raises(ValueError):
            raced.trace(watch=())

    def test_instrumentation_restored_after_exit(self):
        orig = OneLock.__setattr__
        with raced.trace(watch=(OneLock,)):
            assert OneLock.__setattr__ is not orig
        assert OneLock.__setattr__ is orig

    def test_wrapped_locks_survive_the_window(self):
        # instances born inside the trace keep their TracedLock after
        # exit — it must stay a working lock
        with raced.trace(watch=(OneLock,)):
            obj = OneLock()
        with obj._lock:
            assert obj._lock.locked()
        assert not obj._lock.locked()

    def test_default_watch_importable(self):
        classes = raced.default_serving_watch()
        assert len(classes) >= 8
        assert all(isinstance(c, type) for c in classes)


@pytest.mark.slow
class TestSoakSmoke:
    """``serve --load trace --soak-s N`` (ISSUE 15 satellite): the
    long-horizon soak runs diurnal trace waves with the race detector
    armed and asserts host stability — zero race/inversion findings,
    flat thread count, bounded RSS, all requests terminal. The small
    slice of ROADMAP item 5's soak remainder that fits CI."""

    def test_trace_soak_stays_stable(self, monkeypatch, capsys):
        import json as _json
        import sys as _sys

        from akka_allreduce_tpu.cli import main
        monkeypatch.setattr(_sys, "argv", [
            "aat", "serve", "--load", "trace", "--soak-s", "10",
            "--arrival-rate", "50", "--requests", "10",
            "--arrival-curve", "diurnal", "--max-new-tokens", "6",
            "--slots", "2", "--d-model", "32", "--n-layers", "1",
            "--n-heads", "4", "--d-ff", "64", "--vocab", "61",
            "--max-seq", "64", "--prompt-len", "4:8",
            "--tenant-count", "2", "--prefix-len", "4"])
        assert main() == 0
        report = _json.loads(capsys.readouterr().out)
        assert report["soak"] == "ok"
        assert report["waves"] >= 2
        assert report["failures"] == []
        assert report["raced"]["races"] == 0
        assert report["raced"]["inversions"] == 0
        assert report["raced"]["writes_seen"] > 0
        assert report["threads"][-1] <= report["threads"][0]

    def test_soak_requires_trace_load(self, monkeypatch, capsys):
        import sys as _sys

        from akka_allreduce_tpu.cli import main
        monkeypatch.setattr(_sys, "argv", [
            "aat", "serve", "--soak-s", "5"])
        assert main() == 2


class TestLiveRegistry:
    def test_registry_clean_under_scrape_load(self):
        """The integration pin: the real metrics registry mutated by
        an owner loop while a scraper renders — the cross-thread
        pattern the telemetry plane documents — must be race-free
        under the detector (the locks Histogram/MetricsRegistry carry
        are exactly why)."""
        from akka_allreduce_tpu.telemetry.registry import (
            Counter,
            Gauge,
            Histogram,
            MetricsRegistry,
        )
        with raced.trace(watch=(MetricsRegistry, Histogram, Counter,
                                Gauge)) as probe:
            reg = MetricsRegistry()
            hist = reg.histogram("lat_seconds")
            cnt = reg.counter("reqs_total")
            stop = threading.Event()

            def owner():
                i = 0
                while not stop.is_set():
                    hist.record(i * 1e-3)
                    cnt.inc()
                    i += 1

            def scraper():
                while not stop.is_set():
                    reg.to_prometheus_text()
                    reg.to_json()

            ts = [threading.Thread(target=owner),
                  threading.Thread(target=scraper)]
            for t in ts:
                t.start()
            stop_timer = threading.Timer(0.3, stop.set)
            stop_timer.start()
            for t in ts:
                t.join(timeout=10)
            stop_timer.join(timeout=10)
        report = probe.report()
        assert report.locks_wrapped >= 2
        assert report.writes_seen > 10
        report.assert_clean()
