"""Host resource sampler (runtime/metrics.py) — the framework's
equivalent of the reference's ClusterMetricsExtension + Sigar host
CPU/memory sampling (reference: application.conf:26-34, build.sbt:26)."""

import os
import time

from akka_allreduce_tpu.runtime.metrics import HostResourceSampler
from akka_allreduce_tpu.runtime.tracing import Tracer


class TestHostResourceSampler:
    def test_samples_rss_and_cpu_into_tracer(self):
        tracer = Tracer()
        with HostResourceSampler(interval_s=0.05, tracer=tracer) as s:
            # burn a little CPU and memory so both gauges move
            junk = [bytearray(4 << 20) for _ in range(8)]
            t0 = time.monotonic()
            x = 0
            while time.monotonic() - t0 < 0.4:
                x += sum(range(1000))
        res = s.summary()
        assert junk and x
        assert res["samples"] >= 2
        # this test process holds tens of MB at minimum
        assert res["peak_rss_mb"] > 10
        assert res["mean_cpu_pct"] is not None
        assert res["mean_cpu_pct"] > 0
        events = [e for e in tracer.events if e.kind == "host_resources"]
        assert len(events) == res["samples"]
        assert all(e.fields["rss_mb"] > 0 for e in events)

    def test_multi_pid_sum_and_dead_pid_tolerated(self):
        # a dead pid contributes nothing rather than raising
        with HostResourceSampler(pids=[os.getpid(), 2 ** 22 + 12345],
                                 interval_s=0.05) as s:
            time.sleep(0.15)
        res = s.stop()  # idempotent
        assert res["peak_rss_mb"] > 10
        assert res["samples"] >= 1
