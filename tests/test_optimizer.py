"""Optimizer-schedule knobs: warmup+cosine LR and global-norm clipping.

Both are observable through the train step: the schedule through the
step-indexed learning rate the update applies, clipping through the
bounded parameter delta under an adversarially large gradient.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from akka_allreduce_tpu.models.train import (
    TrainConfig,
    make_lr_schedule,
    make_train_state,
    make_train_step,
)
from akka_allreduce_tpu.models.transformer import TransformerConfig
from akka_allreduce_tpu.parallel.mesh import MeshSpec, make_device_mesh

MCFG = TransformerConfig(vocab_size=31, d_model=32, n_heads=4, n_layers=1,
                         d_ff=64, max_seq=32)


def tokens(b=2, t=32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, 31, size=(b, t), dtype=np.int32))


class TestSchedule:
    def test_constant_by_default_preserves_state_structure(self):
        # "constant" must return the PLAIN float: a schedule wrapper would
        # change the optimizer-state pytree and break restore of every
        # checkpoint saved before the schedule feature existed
        import optax
        cfg = TrainConfig(model=MCFG, learning_rate=3e-4)
        assert make_lr_schedule(cfg) == pytest.approx(3e-4)
        old = optax.adamw(3e-4).init({"w": jnp.zeros(2)})
        new = optax.adamw(make_lr_schedule(cfg)).init({"w": jnp.zeros(2)})
        assert jax.tree.structure(old) == jax.tree.structure(new)

    def test_warmup_cosine_shape(self):
        cfg = TrainConfig(model=MCFG, learning_rate=1e-3,
                          lr_schedule="cosine", warmup_steps=100,
                          total_steps=1100)
        sched = make_lr_schedule(cfg)
        assert float(sched(0)) == pytest.approx(0.0, abs=1e-5)
        assert float(sched(100)) == pytest.approx(1e-3, rel=1e-3)
        # cosine tail decays monotonically to 0 at total_steps
        mid, end = float(sched(600)), float(sched(1100))
        assert 0 <= end < mid < 1e-3

    def test_cosine_requires_total_steps(self):
        cfg = TrainConfig(model=MCFG, lr_schedule="cosine",
                          warmup_steps=10)
        with pytest.raises(ValueError, match="total_steps"):
            make_lr_schedule(cfg)

    def test_unknown_schedule_rejected(self):
        cfg = TrainConfig(model=MCFG, lr_schedule="linear")
        with pytest.raises(ValueError, match="lr_schedule"):
            make_lr_schedule(cfg)

    @pytest.mark.slow
    def test_warmup_applies_in_train_step(self):
        """During warmup the effective LR is tiny: the first-step update
        under warmup must be far smaller than without it."""
        mesh = make_device_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
        toks = tokens()

        def first_step_delta(**kw):
            cfg = TrainConfig(model=MCFG, learning_rate=1e-2,
                              bucket_elems=256, grad_axes=("dp",), **kw)
            params, opt_state, opt = make_train_state(
                jax.random.key(0), cfg, mesh)
            before = jax.tree.map(jnp.copy, params)
            step = make_train_step(cfg, mesh, opt)
            params, _, _ = step(params, opt_state, toks)
            return max(float(jnp.abs(a - b).max()) for a, b in zip(
                jax.tree.leaves(before), jax.tree.leaves(params)))

        plain = first_step_delta()
        warm = first_step_delta(lr_schedule="cosine", warmup_steps=1000,
                                total_steps=2000)
        assert warm < plain / 50, (warm, plain)


class TestClipping:
    @pytest.mark.slow
    def test_clip_bounds_update_under_huge_grads(self):
        """Scale the loss by 1e6: without clipping adam's first-step
        update is ~lr regardless, but the INNER clipped gradient must obey
        the global-norm bound — observable via the grad-norm metric."""
        mesh = make_device_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
        cfg = TrainConfig(model=MCFG, learning_rate=1e-3,
                          bucket_elems=256, grad_axes=("dp",),
                          clip_norm=1.0)
        params, opt_state, opt = make_train_state(jax.random.key(0), cfg,
                                                  mesh)
        step = make_train_step(cfg, mesh, opt)
        params, opt_state, m = step(params, opt_state, tokens())
        assert np.isfinite(float(m["loss"]))

        # the transformation chain must include clipping: applying the
        # optimizer directly to a huge gradient yields a bounded step
        huge = jax.tree.map(lambda p: jnp.full_like(p, 1e6), params)
        updates, _ = opt.update(huge, opt_state, params)
        gnorm = jnp.sqrt(sum(jnp.sum(u.astype(jnp.float32) ** 2)
                             for u in jax.tree.leaves(updates)))
        # adamw normalises, so the per-step delta stays ~lr-scale; the
        # point is it is finite and small, not 1e6-scale
        assert float(gnorm) < 1.0

    @pytest.mark.slow
    def test_training_still_learns_with_schedule_and_clip(self):
        mesh = make_device_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
        cfg = TrainConfig(model=MCFG, learning_rate=5e-3,
                          bucket_elems=256, grad_axes=("dp",),
                          lr_schedule="cosine", warmup_steps=2,
                          total_steps=40, clip_norm=1.0)
        params, opt_state, opt = make_train_state(jax.random.key(1), cfg,
                                                  mesh)
        step = make_train_step(cfg, mesh, opt)
        toks = tokens(b=4)
        losses = []
        for _ in range(12):
            params, opt_state, m = step(params, opt_state, toks)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses


class TestOptimizerFamilies:
    """--optimizer families (models/train.py make_optimizer). adamw's
    learning behavior is pinned throughout this file; these cover the
    beyond-reference families and the family-independent step counter
    the int8 transport's quant seed rides on."""

    def _losses(self, fam, lr=5e-3, steps=10, **cfg_kw):
        mesh = make_device_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
        cfg = TrainConfig(model=MCFG, learning_rate=lr, bucket_elems=256,
                          grad_axes=("dp",), optimizer=fam, **cfg_kw)
        params, opt_state, opt = make_train_state(jax.random.key(1), cfg,
                                                  mesh)
        step = make_train_step(cfg, mesh, opt)
        toks = tokens(b=4)
        losses = []
        for _ in range(steps):
            params, opt_state, m = step(params, opt_state, toks)
            losses.append(float(m["loss"]))
        return losses, opt_state

    @pytest.mark.parametrize("fam,lr", [
        ("adafactor", 5e-3),
        pytest.param("sgd", 5e-2, marks=pytest.mark.slow),
        pytest.param("lion", 1e-3, marks=pytest.mark.slow),
    ])
    def test_family_learns(self, fam, lr):
        losses, _ = self._losses(fam, lr=lr)
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses

    @pytest.mark.slow  # property pin (state-size accounting), not an
    # edit-loop gate: the fast tier keeps the adafactor learning pin
    def test_adafactor_state_is_factored(self):
        """The point of adafactor: second-moment state is O(r+c) per 2D
        param, not O(r*c) — total optimizer-state bytes must land far
        under adamw's 2x-params."""
        mesh = make_device_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])

        def state_bytes(fam):
            cfg = TrainConfig(model=MCFG, optimizer=fam)
            params, opt_state, _ = make_train_state(jax.random.key(0),
                                                    cfg, mesh)
            return sum(np.asarray(x).nbytes
                       for x in jax.tree.leaves(opt_state)), params

        ada, params = state_bytes("adafactor")
        adam, _ = state_bytes("adamw")
        psize = sum(np.asarray(x).nbytes for x in jax.tree.leaves(params))
        assert adam >= 2 * psize          # m and v, param-shaped
        assert ada < 0.75 * adam, (ada, adam)

    def test_unknown_family_rejected(self):
        from akka_allreduce_tpu.models.train import make_optimizer
        with pytest.raises(ValueError, match="unknown optimizer"):
            make_optimizer(TrainConfig(model=MCFG, optimizer="adagrab"))

    @pytest.mark.slow
    def test_int8_transport_counter_with_sgd(self):
        """sgd has no adam count; the chain's own StepCounterState must
        seed the int8 transport — the family composes with the
        quantized wire and the counter advances."""
        from akka_allreduce_tpu.models.train import StepCounterState
        losses, opt_state = self._losses("sgd", lr=5e-2, steps=6,
                                         grad_transport="int8")
        assert all(np.isfinite(losses))
        counts = [np.asarray(s.count) for s in jax.tree.leaves(
            opt_state, is_leaf=lambda x: isinstance(x, StepCounterState))
            if isinstance(s, StepCounterState)]
        assert counts and counts[0] == 6


@pytest.mark.slow  # property pin: two full compiles; the families'
# learning pins stay the fast gate
class TestWeightDecayMask:
    """Weight decay applies to rank >= 2 tensors only: decaying rmsnorm
    gains toward zero is a quality bug, not regularisation."""

    def _first_update(self, wd):
        mesh = make_device_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
        cfg = TrainConfig(model=MCFG, learning_rate=1e-3,
                          weight_decay=wd)
        params, opt_state, opt = make_train_state(jax.random.key(0), cfg,
                                                  mesh)
        step = make_train_step(cfg, mesh, opt)
        params, _, _ = step(params, opt_state, tokens())
        return params

    def test_norm_gains_exempt_matrices_decayed(self):
        p0 = self._first_update(0.0)
        p1 = self._first_update(0.5)  # huge decay to dominate
        flat0 = dict(jax.tree.flatten_with_path(p0)[0])
        flat1 = dict(jax.tree.flatten_with_path(p1)[0])
        norm_same = matrix_diff = 0
        for path, a in flat0.items():
            bcast = np.asarray(flat1[path])
            if np.asarray(a).ndim >= 2:
                if not np.allclose(np.asarray(a), bcast, atol=1e-7):
                    matrix_diff += 1
            else:
                # 1D leaves: the decay setting must change NOTHING
                np.testing.assert_array_equal(np.asarray(a), bcast,
                                              err_msg=str(path))
                norm_same += 1
        assert norm_same > 0 and matrix_diff > 0, (norm_same, matrix_diff)

    def test_pp_stacked_norm_gains_still_exempt(self):
        """Pipeline stacking turns per-layer (d,) gains into (L, d):
        the mask must rank layer leaves by their UNSTACKED shape or the
        stacked gains get decayed — different (and degraded) training
        under pp than at pp=1 for the same flags."""
        import optax

        from akka_allreduce_tpu.models.train import make_optimizer
        cfg2 = TrainConfig(model=TransformerConfig(
            vocab_size=31, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=16), weight_decay=0.5, learning_rate=0.0)
        opt = make_optimizer(cfg2, stacked_layers=True)
        params = {
            "embed": jnp.ones((31, 32)),
            "layers": {"ln1": jnp.ones((2, 32)),        # stacked gains
                       "wq": jnp.ones((2, 32, 32))},    # stacked matrix
        }
        state = opt.init(params)
        zero_g = jax.tree.map(jnp.zeros_like, params)
        updates, _ = opt.update(zero_g, state, params)
        # lr=0 makes the adam term vanish; only decay moves params
        assert float(jnp.abs(updates["layers"]["ln1"]).max()) == 0.0
        # sanity: the mask DOES decay real matrices (use adamw's decay
        # term directly at lr>0)
        cfg3 = TrainConfig(model=cfg2.model, weight_decay=0.5,
                           learning_rate=1e-2)
        opt3 = make_optimizer(cfg3, stacked_layers=True)
        st3 = opt3.init(params)
        up3, _ = opt3.update(zero_g, st3, params)
        assert float(jnp.abs(up3["layers"]["wq"]).max()) > 0.0
        assert float(jnp.abs(up3["layers"]["ln1"]).max()) == 0.0
        del optax
