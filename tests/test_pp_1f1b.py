"""1F1B pipeline schedule: parity, economics, and guard rails.

The fused one-forward-one-backward schedule (parallel/pp.py
``one_f_one_b``) must produce the SAME loss and synced gradients as the
GPipe path and as the unsharded single-device reference — 1F1B changes
WHEN stage backwards run (bounding activation residency at O(pp)), never
what they compute. ``pp_schedule_stats`` pins the analytic
bubble/residency tradeoff both schedules are chosen by.
"""

import jax
import numpy as np
import pytest

from akka_allreduce_tpu.models.train import (
    TrainConfig,
    make_grad_step,
    make_train_state,
    make_train_step,
)
from akka_allreduce_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    next_token_loss_and_aux,
)
from akka_allreduce_tpu.parallel.ep import MoEConfig
from akka_allreduce_tpu.parallel.mesh import MeshSpec, make_device_mesh
from akka_allreduce_tpu.parallel.pp import pp_schedule_stats, stack_layer_params

from test_train_pp import (  # reuse the gold-parity harness
    MCFG,
    assert_tree_close,
    make_tokens,
    reference_grads,
)


def test_schedule_stats_economics():
    """The analytic tradeoff: 1F1B pays (s-1)/(m+s-1) extra bubble to
    cut activation residency from O(m) to O(s)."""
    st = pp_schedule_stats(s=4, m=8)
    assert st["gpipe"]["bubble_fraction"] == pytest.approx(3 / 11)
    assert st["gpipe"]["resident_microbatches"] == 11
    assert st["1f1b"]["bubble_fraction"] == pytest.approx(6 / 14)
    assert st["1f1b"]["resident_microbatches"] == 7
    # with many microbatches both bubbles shrink and 1f1b residency
    # stays flat — the property that lets m grow on fixed HBM
    st_big = pp_schedule_stats(s=4, m=64)
    assert st_big["1f1b"]["bubble_fraction"] < 0.09
    assert st_big["1f1b"]["resident_microbatches"] == 7
    assert st_big["gpipe"]["resident_microbatches"] == 67


def test_moe_rejected_under_1f1b():
    mcfg = TransformerConfig(
        vocab_size=61, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq=64, moe=MoEConfig(n_experts=4, d_ff=64), moe_every=1)
    mesh = make_device_mesh(MeshSpec(dp=2, pp=2, ep=2))
    cfg = TrainConfig(model=mcfg, bucket_elems=256, microbatches=2,
                      pp_schedule="1f1b")
    with pytest.raises(ValueError, match="dense layers only"):
        make_grad_step(cfg, mesh)


def test_unknown_schedule_rejected():
    mesh = make_device_mesh(MeshSpec(dp=4, pp=2))
    cfg = TrainConfig(model=MCFG, bucket_elems=256, microbatches=2,
                      pp_schedule="zigzag")
    with pytest.raises(ValueError, match="pp_schedule"):
        make_grad_step(cfg, mesh)


@pytest.mark.slow
class Test1F1BGradParity:
    @pytest.mark.parametrize("spec,micro", [
        (MeshSpec(dp=4, pp=2), 2),
        (MeshSpec(dp=2, pp=4), 2),
        (MeshSpec(pp=2, tp=2, sp=2), 2),
    ])
    def test_1f1b_grads_match_unsharded(self, spec, micro):
        mesh = make_device_mesh(spec)
        cfg = TrainConfig(model=MCFG, bucket_elems=256,
                          microbatches=micro, pp_schedule="1f1b")
        tokens = make_tokens(b=8, t=32)

        full = init_transformer(jax.random.key(0), MCFG, tp=spec.tp)
        ref = reference_grads(full, tokens, MCFG)
        ref_stacked = dict(ref, layers=stack_layer_params(ref["layers"]))

        params, _, _ = make_train_state(jax.random.key(0), cfg, mesh)
        grads, metrics = jax.jit(make_grad_step(cfg, mesh))(params, tokens)

        assert_tree_close(grads, ref_stacked)
        assert np.isfinite(float(metrics["loss"]))

    def test_1f1b_matches_gpipe_and_reference_loss(self):
        mesh = make_device_mesh(MeshSpec(dp=2, pp=4))
        tokens = make_tokens(b=8, t=32, seed=3)
        full = init_transformer(jax.random.key(0), MCFG)
        ls, w, _ = next_token_loss_and_aux(full, tokens, MCFG)
        ref_loss = float(ls / w)
        losses = {}
        for sched in ("gpipe", "1f1b"):
            cfg = TrainConfig(model=MCFG, bucket_elems=256,
                              microbatches=2, pp_schedule=sched)
            params, _, _ = make_train_state(jax.random.key(0), cfg, mesh)
            _, metrics = jax.jit(make_grad_step(cfg, mesh))(params,
                                                            tokens)
            losses[sched] = float(metrics["loss"])
        assert losses["gpipe"] == pytest.approx(ref_loss, rel=1e-5)
        assert losses["1f1b"] == pytest.approx(ref_loss, rel=1e-5)

    def test_1f1b_composes_with_remat_and_bf16(self):
        """The O(pp)-residency schedule composed with per-block remat
        and bf16 compute — the long-context memory stack end to end."""
        mesh = make_device_mesh(MeshSpec(dp=2, pp=4))
        cfg = TrainConfig(model=MCFG, bucket_elems=256, microbatches=4,
                          pp_schedule="1f1b", remat=True,
                          compute_dtype="bf16")
        tokens = make_tokens(b=8, t=32, seed=7)
        params, _, _ = make_train_state(jax.random.key(0), cfg, mesh)
        grads, metrics = jax.jit(make_grad_step(cfg, mesh))(params,
                                                            tokens)
        assert np.isfinite(float(metrics["loss"]))
        flat = jax.tree.leaves(grads)
        assert all(np.isfinite(np.asarray(g)).all() for g in flat)

    def test_full_step_runs_and_learns(self):
        mesh = make_device_mesh(MeshSpec(dp=4, pp=2))
        cfg = TrainConfig(model=MCFG, bucket_elems=256, microbatches=2,
                          pp_schedule="1f1b")
        tokens = make_tokens(b=8, t=32, seed=5)
        params, opt_state, opt = make_train_state(
            jax.random.key(2), cfg, mesh)
        step = make_train_step(cfg, mesh, opt)
        losses = []
        for _ in range(3):
            params, opt_state, metrics = step(params, opt_state, tokens)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        assert params["layers"]["wq"].sharding.spec[0] == "pp"
