"""Multi-step block decode (ISSUE 4 tentpole): fusing S decode steps
into one dispatch must be invisible in the tokens.

THE acceptance property: for greedy decode, the block engine
(``EngineConfig.decode_steps = S``) emits BITWISE the tokens of the S=1
engine and of standalone ``generate()`` — across slot churn/refill,
mixed finish reasons (eos / stop / max_tokens) landing mid-block, GQA/
rope/swiglu model families, and the int8 KV cache. Everything S buys
(one dispatch + one readback per S tokens, on-device done-mask
latching) and everything it costs (wasted trailing tokens) must be
unobservable in the output and EXACTLY accounted in the metrics.

Configs deliberately mirror tests/test_serving_engine.py's DENSE/LLAMA
so the parity halves share compiled programs; the no-recompile tests
use their own unique shapes (cold module-level jit caches regardless of
test order, same discipline as TestNoRecompileContract there).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from akka_allreduce_tpu.models.generate import generate
from akka_allreduce_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from akka_allreduce_tpu.serving import (
    EngineConfig,
    Request,
    RequestScheduler,
    SchedulerConfig,
    ServingEngine,
    ServingMetrics,
    serve_loop,
)

DENSE = TransformerConfig(vocab_size=97, d_model=64, n_heads=4,
                          n_layers=2, d_ff=128, max_seq=32)
LLAMA = TransformerConfig(vocab_size=61, d_model=64, n_heads=4,
                          n_kv_heads=2, n_layers=2, d_ff=128, max_seq=32,
                          rope=True, ffn="swiglu")


def make_requests(cfg, n, steps, seed, plens=(3, 5), eos_every=0,
                  budgets=None):
    rng = np.random.default_rng(seed)
    reqs = []
    for rid in range(n):
        plen = plens[rid % len(plens)]
        reqs.append(Request(
            rid=rid,
            prompt=tuple(int(x) for x in rng.integers(
                0, cfg.vocab_size, size=plen)),
            max_new_tokens=(budgets[rid % len(budgets)] if budgets
                            else steps),
            eos_token=(3 if eos_every and rid % eos_every == 0
                       else None),
            submitted_at=0.0))
    return reqs


def run_engine(params, cfg, reqs, slots, decode_steps=1, metrics=None,
               **ecfg_kw):
    engine = ServingEngine(params, cfg,
                           EngineConfig(num_slots=slots,
                                        decode_steps=decode_steps,
                                        **ecfg_kw))
    sched = RequestScheduler(SchedulerConfig(max_queue_depth=len(reqs)),
                             num_slots=slots)
    for r in reqs:
        sched.submit(r)
    return (serve_loop(engine, sched, metrics=metrics,
                       max_dispatches=2000), engine)


def reference(params, cfg, req, kv_dtype=None):
    prompt = jnp.asarray(req.prompt, jnp.int32)[None]
    if req.eos_token is None:
        return np.asarray(generate(params, prompt, cfg,
                                   steps=req.max_new_tokens,
                                   kv_dtype=kv_dtype))[0]
    toks, lengths = generate(params, prompt, cfg,
                             steps=req.max_new_tokens,
                             eos_token=req.eos_token, kv_dtype=kv_dtype)
    return np.asarray(toks)[0][:int(lengths[0])]


class TestBlockParity:
    """Block tokens == single-step tokens == generate() tokens."""

    @pytest.mark.parametrize("s_steps", [2, 4])
    def test_dense_churn_eos_across_s(self, s_steps):
        """More requests than slots + staggered EOS finishes: lanes
        churn through several occupants, finishes land mid-block, and
        every request's stream is bitwise generate()'s."""
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 9, steps=7, seed=23, eos_every=2)
        single, _ = run_engine(params, DENSE, reqs, slots=4)
        block, engine = run_engine(params, DENSE, reqs, slots=4,
                                   decode_steps=s_steps)
        for req in reqs:
            want = reference(params, DENSE, req)
            np.testing.assert_array_equal(
                np.asarray(block[req.rid][0], np.int32), want,
                err_msg=f"rid={req.rid} vs generate()")
            assert list(block[req.rid][0]) == list(single[req.rid][0])
            assert block[req.rid][1] == single[req.rid][1]
        assert engine.prefill_dispatches == 9  # churn actually happened
        # the block engine paid fewer dispatches for the same tokens
        assert engine.decode_dispatches < sum(
            len(t) for t, _ in block.values())

    def test_mixed_finish_reasons_mid_block(self):
        """eos / stop / max_tokens all landing mid-block (budgets and
        stop positions chosen off the block grid) report the same
        reason and tokens as the S=1 engine."""
        params = init_transformer(jax.random.key(0), DENSE)
        base_reqs = make_requests(DENSE, 4, steps=6, seed=11)
        base, _ = run_engine(params, DENSE, base_reqs, slots=2)
        # stop each request on its own second greedy token (mid-block
        # for S=4), plus an eos request and ragged max_tokens budgets
        reqs = [
            Request(rid=r.rid, prompt=r.prompt, max_new_tokens=6,
                    stop_tokens=(int(np.asarray(base[r.rid][0])[1]),),
                    submitted_at=0.0)
            for r in base_reqs[:2]
        ] + [
            Request(rid=2, prompt=base_reqs[2].prompt, max_new_tokens=5,
                    submitted_at=0.0),
            Request(rid=3, prompt=base_reqs[3].prompt, max_new_tokens=7,
                    eos_token=3, submitted_at=0.0),
        ]
        single, _ = run_engine(params, DENSE, reqs, slots=2)
        block, engine = run_engine(params, DENSE, reqs, slots=2,
                                   decode_steps=4)
        for r in reqs:
            assert list(block[r.rid][0]) == list(single[r.rid][0]), r.rid
            assert block[r.rid][1] == single[r.rid][1], r.rid
        assert {reason for _, reason in block.values()} >= {"stop",
                                                            "max_tokens"}
        assert engine.wasted_tokens > 0  # something really died mid-block

    def test_llama_family_block_decode(self):
        """GQA + rope + swiglu exercise every decode-math branch the
        masked multi-step core mirrors."""
        params = init_transformer(jax.random.key(2), LLAMA)
        reqs = make_requests(LLAMA, 6, steps=6, seed=37)
        results, _ = run_engine(params, LLAMA, reqs, slots=3,
                                decode_steps=4)
        for req in reqs:
            np.testing.assert_array_equal(
                np.asarray(results[req.rid][0], np.int32),
                reference(params, LLAMA, req))

    def test_int8_kv_block_matches_int8_generate(self):
        """The quantized cache's masked write path: block int8 tokens
        equal generate(kv_dtype='int8') bitwise."""
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 4, steps=6, seed=51)
        results, engine = run_engine(params, DENSE, reqs, slots=2,
                                     decode_steps=2, kv_dtype="int8")
        for req in reqs:
            np.testing.assert_array_equal(
                np.asarray(results[req.rid][0], np.int32),
                reference(params, DENSE, req, kv_dtype="int8"))
        assert engine._state["k"].dtype == jnp.int8

    def test_stop_token_width_validation(self):
        params = init_transformer(jax.random.key(0), DENSE)
        engine = ServingEngine(
            params, DENSE, EngineConfig(num_slots=1, decode_steps=2,
                                        max_stop_tokens=2))
        with pytest.raises(ValueError, match="max_stop_tokens"):
            engine.admit(Request(rid=0, prompt=(1, 2),
                                 max_new_tokens=4,
                                 stop_tokens=(1, 2, 3),
                                 submitted_at=0.0))


class TestWastedAccounting:
    """wasted = block steps computed after the lane's done-mask
    latched; exact, not approximate."""

    def test_exact_wasted_counts(self):
        """No churn (slots == requests), budgets straddling the block
        grid: a lane with budget b admitted at a block boundary wastes
        S-1 - (b-1) % S steps in its final block."""
        params = init_transformer(jax.random.key(0), DENSE)
        s_steps = 4
        budgets = (5, 6, 7, 8)
        reqs = make_requests(DENSE, 4, steps=0, seed=11,
                             budgets=budgets)
        metrics = ServingMetrics()
        results, engine = run_engine(params, DENSE, reqs, slots=4,
                                     decode_steps=s_steps,
                                     metrics=metrics)
        want = sum(s_steps - 1 - (b - 1) % s_steps for b in budgets)
        assert engine.wasted_tokens == want == 6
        assert metrics.wasted_tokens == want
        assert metrics.wasted_per_completion.count == 4
        assert metrics.decode_tokens == sum(budgets)
        summary = metrics.summary()
        assert summary["tokens"]["wasted"] == want
        assert summary["wasted_token_rate"] == pytest.approx(
            want / (want + sum(budgets)), abs=1e-4)
        for r in reqs:
            assert len(results[r.rid][0]) == r.max_new_tokens

    def test_single_step_never_wastes(self):
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 4, steps=6, seed=11)
        metrics = ServingMetrics()
        _, engine = run_engine(params, DENSE, reqs, slots=2,
                               metrics=metrics)
        assert engine.wasted_tokens == 0
        assert metrics.wasted_tokens == 0
        assert metrics.summary()["wasted_token_rate"] == 0.0


class TestBlockMetrics:
    """TTFT/TPOT under block emission: TTFT is the first block's
    delivery time; TPOT only measures tokens that arrived after it."""

    def test_tpot_excludes_first_block(self):
        params = init_transformer(jax.random.key(0), DENSE)
        s_steps = 4
        # one request fits entirely in its first block (no cadence
        # sample possible), one spans three blocks
        reqs = make_requests(DENSE, 2, steps=0, seed=11,
                             budgets=(3, 9))
        metrics = ServingMetrics()
        results, _ = run_engine(params, DENSE, reqs, slots=2,
                                decode_steps=s_steps, metrics=metrics)
        assert metrics.ttft_s.count == 2
        assert metrics.tpot_s.count == 1  # only the 9-token request
        assert len(results[0][0]) == 3 and len(results[1][0]) == 9

    def test_s1_metrics_unchanged(self):
        """The n=1 delegation keeps the S=1 engine's metrics exactly as
        before the block path existed."""
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 3, steps=6, seed=11)
        metrics = ServingMetrics()
        results, engine = run_engine(params, DENSE, reqs, slots=2,
                                     metrics=metrics)
        assert metrics.ttft_s.count == 3
        assert metrics.tpot_s.count == 3  # steps > 1 for every request
        assert metrics.decode_tokens == sum(
            len(t) for t, _ in results.values())


class TestMultiStepNoRecompile:
    """The no-recompile contract at S > 1: warmup compiles exactly ONE
    block program per distinct S (plus the per-length prefills), and
    churn/refill at warmed shapes compiles NOTHING.

    Unique model shapes so the module-level jit caches are cold
    regardless of which tests ran earlier in the process."""

    COLD = TransformerConfig(vocab_size=101, d_model=48, n_heads=4,
                             n_layers=2, d_ff=96, max_seq=32)

    def _run(self, params, n_requests, s_steps):
        reqs = make_requests(self.COLD, n_requests, steps=5, seed=7)
        return run_engine(params, self.COLD, reqs, slots=2,
                          decode_steps=s_steps)

    def test_one_program_per_s_and_churn_compiles_nothing(self):
        from akka_allreduce_tpu.analysis.recompile import (CompileLog,
                                                           no_recompiles)
        params = init_transformer(jax.random.key(5), self.COLD)
        with CompileLog() as warm:
            results, engine = self._run(params, 4, s_steps=4)
        assert len(results) == 4
        engine_programs = [n for n in warm.compiled if "engine" in n]
        # one block program + one prefill per distinct prompt length
        # (make_requests plens=(3, 5)); the S=1 _engine_step is never
        # built — the block engine does not touch it
        assert sorted(engine_programs) == [
            "_engine_multi_step", "_engine_prefill", "_engine_prefill"], \
            warm.compiled
        # churn + refill at warmed shapes: a FRESH engine over more
        # requests than slots — zero new programs, by contract
        with no_recompiles("S=4 churn/refill"):
            results, engine = self._run(params, 8, s_steps=4)
        assert len(results) == 8
        assert engine.prefill_dispatches == 8
        # a DIFFERENT S is a different static arg: exactly one new
        # block program, then ITS churn also compiles nothing
        with CompileLog() as warm2:
            results, _ = self._run(params, 4, s_steps=2)
        assert warm2.compiled.count("_engine_multi_step") == 1, \
            warm2.compiled
        assert warm2.compiled.count("_engine_prefill") == 0
        with no_recompiles("S=2 churn at warmed shapes"):
            results, _ = self._run(params, 8, s_steps=2)
        assert len(results) == 8
