"""Multi-host dynamic straggler deadlines: the two flagship halves composed.

The round-2 verdict's top ask: `train --coordinator --deadline-ms` must
run — exact device collectives on each process's local mesh, deadline-
gated masked gradient sync across processes over the coordination-service
KV fabric (runtime/dcn_train.py). The test SIGSTOPs a worker process
mid-run: the survivors must keep training with masked rounds and honest
counts (reference: AllreduceWorker.scala:100-106 straggler tolerance,
application.conf:20 auto-down), and the resumed process must catch up
(replaying retained rounds) and rejoin the mask.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from akka_allreduce_tpu.protocol.remote import free_port
from akka_allreduce_tpu.runtime.dcn_train import (decode_payload,
                                                  encode_payload)

STEPS = 14


class TestPayloadCodec:
    """The DCN payload wire formats (pure host math, no processes)."""

    def test_f32_roundtrip_exact(self):
        vec = np.random.default_rng(0).normal(size=1000).astype(np.float32)
        loss, toks, out = decode_payload(
            encode_payload(vec, 1.5, 64.0, "f32"))
        assert (loss, toks) == (1.5, 64.0)
        np.testing.assert_array_equal(out, vec)

    def test_int8_roundtrip_within_scale(self):
        vec = (np.random.default_rng(1).normal(size=200_000) * 3
               ).astype(np.float32)
        data = encode_payload(vec, 0.5, 8.0, "int8", seed=7)
        # 4x smaller wire (header + scales amortize away)
        assert len(data) < vec.nbytes / 3.5
        loss, toks, out = decode_payload(data)
        assert (loss, toks) == (0.5, 8.0)
        # per-chunk error bounded by one int8 step of that chunk's scale
        from akka_allreduce_tpu.runtime.dcn_train import _INT8_CHUNK
        pad = (-vec.size) % _INT8_CHUNK
        rows = np.pad(vec, (0, pad)).reshape(-1, _INT8_CHUNK)
        scales = np.abs(rows).max(axis=1) / 127.0
        err = np.abs(np.pad(out - vec, (0, pad)).reshape(rows.shape))
        assert (err <= scales[:, None] + 1e-6).all()

    def test_int8_stochastic_rounding_unbiased(self):
        """Mean dequantized value over many rounding seeds converges to
        the true value — the property that makes the quantized wire
        usable for gradients (same argument as the device kernel)."""
        vec = (np.random.default_rng(2).normal(size=4096) * 2
               ).astype(np.float32)
        acc = np.zeros_like(vec, np.float64)
        n = 64
        for s in range(n):
            _, _, out = decode_payload(
                encode_payload(vec, 0.0, 0.0, "int8", seed=100 + s))
            acc += out
        scale = np.abs(vec).max() / 127.0
        bias = np.abs(acc / n - vec)
        assert bias.mean() < 0.2 * scale, bias.mean()

    def test_same_seed_is_deterministic(self):
        """Replay reads recorded bytes, but determinism of the encode
        keeps re-publishes idempotent."""
        vec = np.random.default_rng(3).normal(size=70000).astype(np.float32)
        a = encode_payload(vec, 0.0, 0.0, "int8", seed=5)
        b = encode_payload(vec, 0.0, 0.0, "int8", seed=5)
        assert a == b
        c = encode_payload(vec, 0.0, 0.0, "int8", seed=6)
        assert a != c


def _spawn(port, i, extra=()):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return subprocess.Popen(
        [sys.executable, "-u", "-m", "akka_allreduce_tpu.cli", "train",
         "--platform", "cpu",
         "--coordinator", f"127.0.0.1:{port}",
         "--num-processes", "3", "--process-id", str(i),
         "--steps", str(STEPS), "--batch", "6", "--seq", "16",
         "--d-model", "32", "--n-heads", "4", "--n-layers", "2",
         "--d-ff", "64", "--dp", "2",
         "--deadline-ms", "1500", "--log-every", "1", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        bufsize=1, env=env)


@pytest.mark.slow
@pytest.mark.xdist_group("cluster-procs")
class TestDcnDeadlineChain:
    def test_sigstop_worker_masked_then_rejoins(self):
        """3 processes; SIGSTOP process 2 at step 4, SIGCONT at step 10.

        Asserts the verdict's done-criteria: survivors keep training with
        masked rounds (honest 1/3-masked narration), losses stay finite,
        the stopped process catches up and exits cleanly, and post-resume
        rounds run unmasked again."""
        port = free_port()
        procs = [_spawn(port, i) for i in range(3)]
        lines: list[str] = []
        state = {"stopped": False, "resumed": False}

        def pump():
            for line in procs[0].stdout:
                lines.append(line.rstrip())
                if "step    4" in line and not state["stopped"]:
                    state["stopped"] = True
                    os.kill(procs[2].pid, signal.SIGSTOP)
                if "step   10" in line and state["stopped"] \
                        and not state["resumed"]:
                    state["resumed"] = True
                    os.kill(procs[2].pid, signal.SIGCONT)

        t = threading.Thread(target=pump)
        t.start()
        rcs = []
        deadline = time.time() + 480
        try:
            for p in procs:
                rcs.append(p.wait(timeout=max(5, deadline - time.time())))
        finally:
            for p in procs:
                if p.poll() is None:
                    try:
                        os.kill(p.pid, signal.SIGCONT)
                    except OSError:
                        pass
                    p.kill()
        t.join(timeout=15)
        out = "\n".join(lines)
        tails = [p.stdout.read() or "" for p in procs]
        assert state["stopped"] and state["resumed"], out
        assert rcs == [0, 0, 0], (rcs, out, tails[1][-800:],
                                  tails[2][-800:])
        # survivors trained through the stall with honest masked counts
        masked = [ln for ln in lines if "[masked 1/3" in ln]
        assert masked, out
        # every narrated loss stayed finite
        for ln in lines:
            if "loss" in ln and "step" in ln:
                val = float(ln.split("loss")[1].split()[0])
                assert val == val and val < 1e9, ln
        # the run completed all steps and summarised the lossy rounds
        assert f"step   {STEPS}" in out, out
        summary = [ln for ln in lines if "lossy rounds" in ln]
        assert summary and int(summary[0].split(":")[1].split("/")[0]) >= 1
        # after SIGCONT the cluster converged back to unmasked rounds:
        # the LAST narrated round has everyone back in the mask
        last_masked = [ln for ln in lines if "[masked" in ln][-1]
        assert "[masked 0/3" in last_masked, out

    def test_pipelined_max_lag_window(self):
        """2 processes with --max-lag 3: up to 3 rounds in flight
        (bounded-staleness streaming, the reference's maxLag in this
        topology). All 10 rounds must apply — including the window tail
        drained after the loop — with finite losses."""
        port = free_port()
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        procs = [subprocess.Popen(
            [sys.executable, "-u", "-m", "akka_allreduce_tpu.cli",
             "train", "--platform", "cpu",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(i),
             "--steps", "10", "--batch", "4", "--seq", "16",
             "--d-model", "32", "--n-heads", "4", "--n-layers", "1",
             "--d-ff", "64", "--dp", "2", "--max-lag", "3",
             "--deadline-ms", "2000", "--log-every", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for i in range(2)]
        outs = []
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=420)
            outs.append(out)
            assert p.returncode == 0, f"proc {i}:\n{out[-2000:]}"
        assert "step   10" in outs[0], outs[0]
        # the tail of the window drains after the loop
        assert "(drained)" in outs[0], outs[0]
        assert "lossy rounds: 0/10" in outs[0], outs[0]
        for ln in outs[0].splitlines():
            if "loss" in ln and "step" in ln:
                v = float(ln.split("loss")[1].split()[0])
                assert v == v and v < 1e9, ln

    def test_beyond_retention_rejoins_via_snapshot(self, tmp_path):
        """SIGSTOP a worker LONGER than the retention window (retain 8,
        stall spans ~14 masked rounds): replay is impossible, so the
        woken worker must run the snapshot-rejoin protocol — request a
        checkpoint, the master force-saves and publishes it, the worker
        restores, rebases, replays the fresh tail, and rejoins the mask.
        The reference analog: a cold worker re-initialized by the master
        (AllreduceWorker.scala:87-89)."""
        port = free_port()
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        ckpt = str(tmp_path / "ckpt")
        procs = [subprocess.Popen(
            [sys.executable, "-u", "-m", "akka_allreduce_tpu.cli",
             "train", "--platform", "cpu",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(i),
             "--steps", "26", "--batch", "4", "--seq", "16",
             "--d-model", "32", "--n-heads", "4", "--n-layers", "1",
             "--d-ff", "64", "--dp", "2", "--retain-rounds", "8",
             "--ckpt-dir", ckpt, "--ckpt-every", "4",
             "--deadline-ms", "400", "--log-every", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            bufsize=1, env=env) for i in range(2)]
        lines: list[str] = []
        state = {"stopped": False, "resumed": False}

        def pump():
            for line in procs[0].stdout:
                lines.append(line.rstrip())
                if "step    4:" in line and not state["stopped"]:
                    state["stopped"] = True
                    os.kill(procs[1].pid, signal.SIGSTOP)
                # stall across ~14 masked rounds — well past retain 8
                if "step   18:" in line and state["stopped"] \
                        and not state["resumed"]:
                    state["resumed"] = True
                    os.kill(procs[1].pid, signal.SIGCONT)

        t = threading.Thread(target=pump)
        t.start()
        rcs = []
        deadline = time.time() + 480
        try:
            for p in procs:
                rcs.append(p.wait(timeout=max(5, deadline - time.time())))
        finally:
            for p in procs:
                if p.poll() is None:
                    try:
                        os.kill(p.pid, signal.SIGCONT)
                    except OSError:
                        pass
                    p.kill()
        t.join(timeout=15)
        out0 = "\n".join(lines)
        out1 = procs[1].stdout.read() or ""
        assert state["stopped"] and state["resumed"], out0
        assert rcs == [0, 0], (rcs, out0[-1500:], out1[-1500:])
        # the master served the protocol; the worker rejoined through it
        assert "served rejoin snapshot at step" in out0, out0
        assert "elastic rejoin via checkpoint snapshot" in out1, out1
        # post-rejoin rounds run unmasked again
        last_masked = [ln for ln in lines if "[masked" in ln][-1]
        assert "[masked 0/2" in last_masked, out0

    def test_killed_master_fails_workers_in_seconds(self):
        """SIGKILL the master mid-run: workers must fail within seconds
        — not spin out the multi-minute 2*deadline+barrier timeout. The
        reference's master death halts the run through the 10 s failure
        detector (application.conf:20); parity is failing FAST.

        Two detectors cover this, whichever fires first: killing the
        master here also kills the coordination service it hosts, so
        JAX's own service failure detector terminates workers instantly;
        when the service survives the master trainer (external service,
        or a wedged master process), the trainer-level heartbeat watch
        fires within --master-timeout-s instead (pinned in-process by
        tests/test_dcn_protocol.py::TestMasterLiveness)."""
        port = free_port()
        procs = [_spawn(port, i, extra=("--master-timeout-s", "3"))
                 for i in range(3)]
        lines: list[str] = []
        state = {"killed_at": 0.0}

        def pump():
            for line in procs[0].stdout:
                lines.append(line.rstrip())
                if "step    3" in line and not state["killed_at"]:
                    state["killed_at"] = time.time()
                    procs[0].kill()

        t = threading.Thread(target=pump)
        t.start()
        outs = ["", ""]
        try:
            for i in (1, 2):
                out, _ = procs[i].communicate(timeout=240)
                outs[i - 1] = out
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        t.join(timeout=15)
        died_at = time.time()
        assert state["killed_at"], "\n".join(lines)
        # workers exited non-zero, quickly, and said why
        assert procs[1].returncode not in (0, None)
        assert procs[2].returncode not in (0, None)
        assert died_at - state["killed_at"] < 60, (
            died_at - state["killed_at"])
        assert any("heartbeat" in o  # trainer-level watch
                   or "coordination service" in o  # JAX failure detector
                   for o in outs), outs

    def test_straggle_prob_simulation_runs(self):
        """2 processes with --straggle-prob AND --int8-grads: simulated
        late publishes via the real wall clock produce masked rounds
        without signal games, over the quantized DCN wire (int8 payloads
        + int8 local transport); both processes exit cleanly with finite
        losses."""
        port = free_port()
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        procs = [subprocess.Popen(
            [sys.executable, "-u", "-m", "akka_allreduce_tpu.cli",
             "train", "--platform", "cpu",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(i),
             "--steps", "8", "--batch", "4", "--seq", "16",
             "--d-model", "32", "--n-heads", "4", "--n-layers", "1",
             "--d-ff", "64", "--dp", "2", "--int8-grads",
             "--bucket-elems", "65536",
             "--deadline-ms", "700", "--straggle-prob", "0.45",
             "--log-every", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for i in range(2)]
        outs = []
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=420)
            outs.append(out)
            assert p.returncode == 0, f"proc {i}:\n{out[-2000:]}"
        # seeded straggle draws: with p=0.45 over 8 rounds the non-master
        # process misses at least one deadline in practice; assert the
        # machinery reported at least one masked round
        assert "[masked 1/2" in outs[0], outs[0]
        assert "lossy rounds" in outs[0]
