"""Liveness detector -> elastic recovery, end to end.

The reference's fault chain is: failure detector downs the unreachable
member (reference: application.conf:20), deathwatch shrinks the peer map
(AllreduceMaster.scala:46-52), thresholds keep rounds completing. This
framework adds the re-formation half (runtime/elastic.py). Here the two are
wired together the way a deployment would: the transport heartbeat detector
(protocol/tcp.py) fires deathwatch on a hung peer, which drives
ElasticController -> shrunken mesh -> resharded training state -> training
continues on the survivors.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from akka_allreduce_tpu.models.train import (
    TrainConfig,
    make_train_state,
    make_train_step,
    param_specs,
    place_opt_state,
)
from akka_allreduce_tpu.models.transformer import TransformerConfig
from akka_allreduce_tpu.parallel.mesh import MeshSpec
from akka_allreduce_tpu.protocol.tcp import TcpRouter
from akka_allreduce_tpu.runtime.elastic import ElasticController, reshard

MCFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_seq=16)


def make_tokens(b, t, seed):
    return jnp.asarray(np.random.default_rng(seed).integers(
        0, MCFG.vocab_size, size=(b, t), dtype=np.int32))


@pytest.mark.slow
class TestDetectorDrivesReshard:
    def test_hung_host_downed_then_training_reforms(self):
        """Host 0 (controller) trains on an 8-device dp mesh spanning two
        'hosts' of 4 virtual devices. Host 1's agent process hangs (stops
        polling); the heartbeat detector downs it; deathwatch drives the
        elastic controller: mesh shrinks to host 0's 4 devices, state
        reshards, training keeps stepping."""
        devices = jax.devices()[:8]
        cfg = TrainConfig(model=MCFG, learning_rate=1e-2, bucket_elems=512,
                          grad_axes=("dp",))

        events = []
        controller = ElasticController(
            MeshSpec(dp=8), total_hosts=2, devices_per_host=4,
            min_fraction=0.5,
            on_reform=lambda mesh, gen: events.append((gen, mesh)))
        rank_of_addr = {}

        with TcpRouter(role="master", heartbeat_interval_s=0.05,
                       unreachable_after_s=0.4) as a:
            def on_terminated(ref):
                controller.handle_member_lost(
                    rank_of_addr[tuple(ref.addr)], devices)

            a.on_terminated = on_terminated

            with TcpRouter(role="worker", heartbeat_interval_s=0.05) as b:
                b.register("agent1", handler=lambda m: None)
                b.dial(a.addr)
                rank_of_addr[tuple(b.addr)] = 1

                # both hosts up: full 8-device mesh
                controller.tracker.member_up(0)
                mesh = controller.handle_member_up(1, devices)
                assert mesh.devices.size == 8
                params, opt_state, opt = make_train_state(
                    jax.random.key(0), cfg, mesh)
                step = make_train_step(cfg, mesh, opt)
                tokens = make_tokens(8, 16, seed=1)
                params, opt_state, m0 = step(params, opt_state, tokens)
                assert np.isfinite(float(m0["loss"]))

                # host 1 hangs: b stops polling; a's detector downs it,
                # deathwatch -> elastic reshard
                events.clear()  # drop the join-time reform event
                deadline = time.monotonic() + 3.0
                while not events and time.monotonic() < deadline:
                    a.poll(0.05)
                assert events, "detector never downed the hung host"
                gen, new_mesh = events[-1]
                assert new_mesh.devices.size == 4
                assert not controller.parked

                # reshard live state onto the survivors and keep training
                before = [np.asarray(x) for x in jax.tree.leaves(params)]
                params = reshard(params, param_specs(MCFG), new_mesh)
                for x, y in zip(before, jax.tree.leaves(params)):
                    np.testing.assert_array_equal(x, np.asarray(y))
                opt_state = place_opt_state(opt, opt_state, params,
                                            new_mesh)
                step2 = make_train_step(cfg, new_mesh, opt)
                losses = []
                for s in range(3):
                    params, opt_state, m = step2(params, opt_state,
                                                 make_tokens(8, 16, seed=s))
                    losses.append(float(m["loss"]))
                assert all(np.isfinite(x) for x in losses), losses
