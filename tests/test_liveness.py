"""Liveness failure detection: hung-but-connected peers get downed.

The closed-socket path (deathwatch on disconnect) cannot see a peer that
hangs without closing its socket — SIGSTOP, deadlock, GC pause. The
transport-level heartbeat detector (protocol/tcp.py) downs such peers after
``unreachable_after_s`` of silence, the TCP rendering of the reference's
``auto-down-unreachable-after = 10s`` (reference: application.conf:20).

Tests: (1) a silent-but-connected peer is downed within the window; (2) a
healthy polling peer is NOT downed; (3) end-to-end — a 4-worker lossy
cluster with one worker SIGSTOPped keeps completing rounds and the master
logs the auto-down.
"""

import os
import re
import signal
import subprocess
import sys
import threading
import time

import pytest

from akka_allreduce_tpu.protocol.remote import free_port
from akka_allreduce_tpu.protocol.tcp import TcpRouter


def _drain(stream):
    for _ in stream:
        pass


class TestHeartbeatDetector:
    def test_silent_peer_is_downed(self):
        from akka_allreduce_tpu.runtime.tracing import Tracer

        downed = []
        tracer = Tracer()
        with TcpRouter(role="master", heartbeat_interval_s=0.05,
                       unreachable_after_s=0.4,
                       on_terminated=downed.append, tracer=tracer) as a:
            with TcpRouter(role="worker", heartbeat_interval_s=0.05,
                           unreachable_after_s=0.4) as b:
                b.register("w", handler=lambda m: None)
                b.dial(a.addr)  # Hello goes out; then b never polls again
                deadline = time.monotonic() + 3.0
                while not downed and time.monotonic() < deadline:
                    a.poll(0.05)
        assert len(downed) == 1
        assert downed[0].addr == b.addr
        # the down joins the structured trace stream
        downs = [e for e in tracer.events
                 if e.kind == "peer_unreachable_down"]
        assert len(downs) == 1
        assert downs[0].fields["silent_s"] >= 0.4

    def test_polling_peer_stays_up(self):
        downed = []
        with TcpRouter(role="master", heartbeat_interval_s=0.05,
                       unreachable_after_s=0.4,
                       on_terminated=downed.append) as a:
            with TcpRouter(role="worker", heartbeat_interval_s=0.05,
                           unreachable_after_s=0.4) as b:
                b.register("w", handler=lambda m: None)
                b.dial(a.addr)
                end = time.monotonic() + 1.5
                while time.monotonic() < end:
                    a.poll(0.01)
                    b.poll(0.01)
        assert downed == []

    def test_detector_disabled_node_stays_detectable(self):
        """A node that disables ITS detector must still send Pings — else
        detector-enabled peers down it during protocol-quiet stretches."""
        downed = []
        with TcpRouter(role="master", heartbeat_interval_s=0.05,
                       unreachable_after_s=0.4,
                       on_terminated=downed.append) as a:
            with TcpRouter(role="worker", heartbeat_interval_s=0.05,
                           unreachable_after_s=None) as b:  # detector off
                b.register("w", handler=lambda m: None)
                b.dial(a.addr)
                end = time.monotonic() + 1.2
                while time.monotonic() < end:
                    a.poll(0.01)
                    b.poll(0.01)  # b polls (pings) but never detects
        assert downed == []

    def test_slow_pinging_healthy_peer_not_downed(self):
        """Asymmetric cadences: the peer pings every 1s, the local window
        is 0.4s — the detector must widen to 2x the peer's ADVERTISED
        cadence (carried in Ping frames) instead of falsely downing a
        healthy node between its pings."""
        downed = []
        with TcpRouter(role="master", heartbeat_interval_s=0.05,
                       unreachable_after_s=0.4,
                       on_terminated=downed.append) as a:
            with TcpRouter(role="worker", heartbeat_interval_s=1.0,
                           unreachable_after_s=None) as b:
                b.register("w", handler=lambda m: None)
                b.dial(a.addr)
                end = time.monotonic() + 1.8
                while time.monotonic() < end:
                    a.poll(0.01)
                    b.poll(0.01)  # pings only every ~1s
        assert downed == []

    def test_window_shorter_than_ping_cadence_rejected(self):
        with pytest.raises(ValueError, match="heartbeat_interval"):
            TcpRouter(role="master", heartbeat_interval_s=2.0,
                      unreachable_after_s=1.0)

    def test_detector_disabled_never_downs(self):
        downed = []
        with TcpRouter(role="master", heartbeat_interval_s=0.05,
                       unreachable_after_s=None,
                       on_terminated=downed.append) as a:
            with TcpRouter(role="worker") as b:
                b.register("w", handler=lambda m: None)
                b.dial(a.addr)
                end = time.monotonic() + 0.8
                while time.monotonic() < end:
                    a.poll(0.01)
        assert downed == []


@pytest.mark.slow
@pytest.mark.xdist_group("cluster-procs")
@pytest.mark.slow
@pytest.mark.xdist_group("cluster-procs")
class TestMutualDialLiveness:
    """A mutually-dialed pair carries TWO TCP connections (each side
    sends on the one it dialed, receives on the inbound one) — the
    round-0 scatter burst makes this the NORMAL worker-worker topology.
    Liveness must be per-PEER, not per-connection: a per-conn tracker
    watches the never-written dialed conn and falsely downs every such
    peer one unreachable window after the first exchange (caught as the
    whole-cluster stall in the SIGSTOP test below: all three survivors
    downed each other in a single detector sweep)."""

    def test_mutually_dialed_pair_survives_a_quiet_stretch(self):
        downs = []
        a = TcpRouter(role="a", heartbeat_interval_s=0.2,
                      unreachable_after_s=0.6,
                      on_terminated=lambda ref: downs.append(("a", ref)))
        b = TcpRouter(role="b", heartbeat_interval_s=0.2,
                      unreachable_after_s=0.6,
                      on_terminated=lambda ref: downs.append(("b", ref)))
        try:
            a.dial(b.addr)
            b.dial(a.addr)  # duplicate pair: 2 conns, asymmetric writes
            deadline = time.monotonic() + 2.0  # >3 unreachable windows
            while time.monotonic() < deadline:
                a.poll(0.01)
                b.poll(0.01)
            assert downs == [], downs  # pings alone must keep the pair up
        finally:
            a.close()
            b.close()

    def test_dead_peer_with_duplicate_conns_is_downed_once(self):
        downs = []
        a = TcpRouter(role="a", heartbeat_interval_s=0.2,
                      unreachable_after_s=0.6,
                      on_terminated=downs.append)
        b = TcpRouter(role="b", heartbeat_interval_s=0.2,
                      unreachable_after_s=0.6)
        a.dial(b.addr)
        b.dial(a.addr)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 0.5:
            a.poll(0.01)
            b.poll(0.01)
        b.close()  # real death: BOTH of the pair's conns drop
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and not downs:
            a.poll(0.01)
        a.close()
        # exactly one deathwatch fire for the peer, not one per conn
        assert [d.addr for d in downs] == [tuple(b.addr)], downs


@pytest.mark.slow
@pytest.mark.xdist_group("cluster-procs")
class TestSigstopCluster:
    def test_lossy_cluster_survives_sigstopped_worker(self):
        """4 workers, thresholds 0.75, one worker SIGSTOPped mid-run: all
        rounds must still complete (threshold semantics) AND the master
        must auto-down the hung worker (liveness detection) — the scenario
        the reference's failure detector + thresholds exist for
        (reference: application.conf:20; SURVEY.md §5.3)."""
        port = free_port()
        # Unbounded round budget: the master runs out its --timeout clock
        # instead of finishing early, so the down (at stop + ~1s) always
        # lands mid-run regardless of this box's round rate (observed
        # anywhere from 4/s under load to 130/s idle). The assertion is
        # rate-independent: the master prints the round at which it downs
        # the worker, and the final tally must be strictly larger.
        n, rounds = 4, 1_000_000
        master = subprocess.Popen(
            [sys.executable, "-m", "akka_allreduce_tpu.cli", "master",
             "--port", str(port), "--workers", str(n),
             "--data-size", "1024", "--max-chunk-size", "128",
             "--max-lag", "2", "--th-allreduce", "0.75",
             "--th-reduce", "0.75", "--th-complete", "0.75",
             "--max-round", str(rounds), "--timeout", "45",
             "--heartbeat-interval", "0.4", "--unreachable-after", "6.0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        time.sleep(0.5)
        workers = [subprocess.Popen(
            # -u: the first checkpoint line is the SIGSTOP trigger and
            # must reach the pipe IMMEDIATELY — block-buffered stdout
            # held it back ~8 KB (tens of seconds), landing the stop so
            # late the down had no post-down rounds left to prove
            # liveness against (the flake this comment buries)
            [sys.executable, "-u", "-m", "akka_allreduce_tpu.cli",
             "worker",
             "--master-port", str(port), "--data-size", "1024",
             "--timeout", "50", "--verbose", "--checkpoint", "10",
             "--heartbeat-interval", "0.4", "--unreachable-after", "6.0"],
            # stdout piped ONLY to observe the first checkpoint line (the
            # SIGSTOP trigger); everything else is discarded — an
            # un-drained 64K pipe fills within seconds at --verbose round
            # rates and BLOCKS the writer, stalling the whole cluster
            # (observed as zero rounds completing after the down)
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
            for _ in range(n)]
        victim = workers[-1]
        drains = [threading.Thread(target=_drain, args=(w.stdout,),
                                   daemon=True)
                  for w in workers if w is not victim]
        for t in drains:
            t.start()
        try:
            # stop the victim only once it has demonstrably joined and
            # completed rounds: its first throughput checkpoint print
            # (worker startup is seconds — a timer would race the join)
            line = victim.stdout.readline()
            assert line, "victim produced no output before exiting"
            os.kill(victim.pid, signal.SIGSTOP)
            # a SIGSTOPped victim writes nothing more, but drain anyway so
            # the SIGCONT in the teardown can't block it either
            threading.Thread(target=_drain, args=(victim.stdout,),
                             daemon=True).start()
            m_out, m_err = master.communicate(timeout=60)
            assert "downing unreachable peer" in m_err, (m_out, m_err)
            downs = re.findall(r"worker down at round (\d+)", m_out)
            # a 6s window must only down the SIGSTOPped worker
            # (2s false-downed healthy CPU-starved peers when the 1-core
            # box ran the full suite; the victim's stall is indefinite, so
            # widening costs only detection latency); more downs
            # mean healthy-but-starved peers were falsely detected (the
            # failure mode a too-tight window produces under CPU load)
            assert len(downs) == 1, (downs, m_err)
            down_at = int(downs[0])
            final = int(re.search(r"(\d+)/\d+ rounds", m_out).group(1))
            # rounds kept completing AFTER the hung worker was downed
            assert final > down_at, (down_at, final, m_out)
        finally:
            try:
                os.kill(victim.pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
            for w in workers:
                w.kill()
            master.kill()
