"""Multi-host device plane + DCN transport, proven with real processes.

Two OS processes joined through ``jax.distributed`` (VERDICT r1 next #6):
the child (tests/kv_proc_main.py) runs a psum whose shards live on both
processes' devices, then the full allreduce protocol — master engine and
one worker engine per process — over the coordination-service KV router
(protocol/kv.py, VERDICT r1 next #7). The reference analog is the
real-cluster smoke (reference: scripts/testAllreduceMaster.sc:1-24); the
"seed node" here is the coordination service itself.
"""

import os
import subprocess
import sys

import pytest

from akka_allreduce_tpu.protocol.remote import free_port


@pytest.mark.slow
@pytest.mark.xdist_group("cluster-procs")
class TestTwoProcessCluster:
    def test_psum_and_kv_engines_across_processes(self):
        port = free_port()
        coord = f"127.0.0.1:{port}"
        env = dict(os.environ)
        # 2 virtual CPU devices per process => a 4-device global mesh
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        procs = [subprocess.Popen(
            [sys.executable, os.path.join(os.path.dirname(__file__),
                                          "kv_proc_main.py"),
             str(i), "2", coord],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for i in range(2)]
        outs = []
        for i, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            outs.append(out)
            assert p.returncode == 0, f"proc {i}:\n{out}\n{err}"
        assert "PSUM_OK 4" in outs[0] and "PSUM_OK 4" in outs[1]
        assert "ROUNDS_OK 12" in outs[0]
        assert "SINK_OK" in outs[0] and "SINK_OK" in outs[1]
