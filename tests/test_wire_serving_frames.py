"""Serving wire-format v2 (ISSUE 11): version byte, supervisor frames,
and the hostile-peer read path.

The contract under test: every serving frame (types 7-13) carries
``SERVING_WIRE_VERSION`` right after its message type; a mismatched
version or a truncated/hostile payload raises a :class:`WireError`
subclass with a READABLE message — never a struct/numpy exception from
an arbitrary offset — because protocol/tcp.py converts exactly those
errors into peer failures (deathwatch), and a replica dying mid-write
must surface as a dead peer, not a codec traceback in the router.

Pure codec tests: no jax, no sockets, no subprocesses — the TCP-level
half (oversized frames downing a peer, undecodable frames firing
deathwatch) rides in tests/test_subprocess_fabric.py where real
connections exist.
"""

import struct

import pytest

from akka_allreduce_tpu.protocol import wire
from akka_allreduce_tpu.protocol.wire import (
    CancelFrame,
    CompletionFrame,
    DrainDoneFrame,
    DrainFrame,
    HealthFrame,
    ResumeFrame,
    SERVING_WIRE_VERSION,
    SubmitFrame,
    TruncatedFrame,
    WireError,
    WireVersionError,
    decode,
    encode,
    frame_to_resumable,
    resumable_to_frame,
)


def roundtrip(frame):
    return decode(encode(frame, None), None)


class TestRoundTrips:
    def test_submit(self):
        f = SubmitFrame(rid=7, prompt=(1, 2, 3), max_new_tokens=5,
                        eos_token=2, stop_tokens=(4, 9),
                        deadline=1.5, attempts=2, seed=11)
        assert roundtrip(f) == f

    def test_completion_carries_replica(self):
        f = CompletionFrame(3, (9, 8, 7), "eos", replica=5)
        back = roundtrip(f)
        assert back == f
        assert back.replica == 5

    def test_completion_default_replica_is_sentinel(self):
        back = roundtrip(CompletionFrame(4, (), "watchdog"))
        assert back.replica == -1

    def test_completion_carries_waste(self):
        # wire v3: the cancel ack's discard count — the field that
        # closes the remote-hedge-loser-charged-0 accounting gap
        back = roundtrip(CompletionFrame(9, (), "cancelled",
                                         replica=2, waste=17))
        assert back.waste == 17
        assert roundtrip(CompletionFrame(9, (1,), "eos")).waste == 0
        with pytest.raises(ValueError, match="waste"):
            CompletionFrame(9, (), "cancelled", waste=-1)

    def test_health(self):
        f = HealthFrame(replica=1, occupied=2, free_slots=0,
                        dispatches=55, compiles=7, draining=True,
                        watchdog_trips=2, evictions=3,
                        prefill_programs=4)
        assert roundtrip(f) == f

    def test_health_carries_cancelled_tokens(self):
        # wire v3: the worker's cumulative cancel-discard mirror
        f = HealthFrame(replica=0, occupied=1, free_slots=3,
                        dispatches=9, cancelled_tokens=123)
        assert roundtrip(f).cancelled_tokens == 123

    def test_drain_cancel_drain_done(self):
        assert roundtrip(DrainFrame()) == DrainFrame()
        assert roundtrip(CancelFrame(42)) == CancelFrame(42)
        assert roundtrip(DrainDoneFrame(1, 3)) == DrainDoneFrame(1, 3)

    def test_resume_full(self):
        f = ResumeFrame(rid=4, prompt=(1, 2), max_new_tokens=8,
                        generated=(5, 6, 7), eos_token=3,
                        stop_tokens=(9,), deadline=0.25, attempts=1,
                        seed=13, replica=0)
        assert roundtrip(f) == f

    def test_resume_optionals_none(self):
        f = ResumeFrame(rid=4, prompt=(1,), max_new_tokens=8)
        back = roundtrip(f)
        assert back.eos_token is None
        assert back.deadline is None
        assert back.seed is None
        assert back.generated == ()


class TestResumableMapping:
    def test_snapshot_roundtrip(self):
        from akka_allreduce_tpu.serving.engine import ResumableRequest
        from akka_allreduce_tpu.serving.scheduler import Request
        rr = ResumableRequest(
            req=Request(rid=9, prompt=(3, 1, 4), max_new_tokens=6,
                        eos_token=2, stop_tokens=(5,), attempts=1,
                        seed=77),
            generated=(8, 8), slot=1)
        frame = resumable_to_frame(rr, replica=1)
        assert frame.replica == 1
        back = frame_to_resumable(roundtrip(frame))
        assert back.req.rid == 9
        assert back.req.prompt == (3, 1, 4)
        assert back.req.attempts == 1
        assert back.req.seed == 77
        assert back.generated == (8, 8)
        assert back.slot == -1  # no slot until the target admits


class TestVersioning:
    @pytest.mark.parametrize("frame", [
        SubmitFrame(rid=0, prompt=(5,), max_new_tokens=1),
        CompletionFrame(0, (), "eos"),
        HealthFrame(0, 0, 2, 0),
        DrainFrame(),
        CancelFrame(0),
        ResumeFrame(rid=0, prompt=(5,), max_new_tokens=2),
        DrainDoneFrame(0, 0),
    ])
    def test_every_serving_frame_carries_the_version_byte(self, frame):
        buf = encode(frame, None)
        assert buf[1] == SERVING_WIRE_VERSION

    def test_version_mismatch_is_a_clear_error(self):
        buf = bytearray(encode(CompletionFrame(1, (2,), "eos"), None))
        buf[1] = SERVING_WIRE_VERSION + 1
        with pytest.raises(WireVersionError,
                           match="different builds"):
            decode(bytes(buf), None)

    def test_allreduce_frames_stay_unversioned(self):
        # the training plane's frames (types 0-6) predate versioning;
        # their layout must not have shifted under this PR
        ping = wire.Ping(2.0)
        back = decode(encode(ping, None), None)
        assert isinstance(back, wire.Ping)
        assert back.interval == 2.0


class TestHostileFrames:
    def test_truncated_submit_header(self):
        buf = encode(SubmitFrame(rid=0, prompt=(5,),
                                 max_new_tokens=1), None)
        with pytest.raises(TruncatedFrame, match="truncated"):
            decode(buf[:6], None)

    def test_lying_payload_counts(self):
        # header claims 1000 prompt tokens; payload carries 1 — the
        # np.frombuffer ValueError must never escape raw
        f = SubmitFrame(rid=0, prompt=(5,), max_new_tokens=1)
        buf = bytearray(encode(f, None))
        # n_prompt is the I at offset 2 + "<qIiBiBd" in the v2 layout
        off = 2 + struct.calcsize("<qIiBiBd")
        struct.pack_into("<I", buf, off, 1000)
        with pytest.raises(TruncatedFrame):
            decode(bytes(buf), None)

    def test_lying_completion_counts(self):
        buf = bytearray(encode(CompletionFrame(1, (2, 3), "eos"),
                               None))
        off = 2 + struct.calcsize("<qiIB")
        struct.pack_into("<I", buf, off, 1 << 20)
        with pytest.raises(TruncatedFrame):
            decode(bytes(buf), None)

    def test_unknown_type_is_wire_error(self):
        with pytest.raises(WireError, match="unknown message type"):
            decode(bytes([250]), None)

    def test_empty_frame(self):
        with pytest.raises(TruncatedFrame):
            decode(b"", None)


# -- seeded codec fuzz (graftcheck PR): the hostile-peer containment ----
#
# Property, not examples: for EVERY v3 serving frame type, across six
# seeds of randomized field values — (1) the round trip is BYTE-exact
# (decode(encode(f)) re-encodes to the identical buffer), (2) every
# strict prefix raises TruncatedFrame, (3) any single corrupted byte
# and any lying length field raises a WireError subclass with a
# readable message — never a raw struct/numpy/unicode exception from
# an arbitrary offset, because protocol/tcp.py turns WireError into a
# dead peer and anything else into a codec traceback in the router.


def _fuzz_frames(rng):
    """One randomized instance of every v3 serving frame type."""
    def toks(n):
        return tuple(int(x) for x in rng.integers(0, 2**31 - 1, size=n))
    return [
        SubmitFrame(
            rid=int(rng.integers(0, 2**62)),
            prompt=toks(int(rng.integers(1, 33))),
            max_new_tokens=int(rng.integers(1, 512)),
            eos_token=(None if rng.random() < 0.5
                       else int(rng.integers(0, 1000))),
            stop_tokens=toks(int(rng.integers(0, 5))),
            deadline=(None if rng.random() < 0.5
                      else float(rng.random() * 100)),
            attempts=int(rng.integers(0, 5)),
            seed=(None if rng.random() < 0.5
                  else int(rng.integers(0, 2**31)))),
        CompletionFrame(
            rid=int(rng.integers(0, 2**62)),
            tokens=toks(int(rng.integers(0, 64))),
            reason=str(rng.choice(["eos", "stop", "max_tokens",
                                   "cancelled", "fault"])),
            replica=int(rng.integers(-1, 8)),
            waste=int(rng.integers(0, 100))),
        HealthFrame(
            replica=int(rng.integers(0, 8)),
            occupied=int(rng.integers(0, 16)),
            free_slots=int(rng.integers(0, 16)),
            dispatches=int(rng.integers(0, 2**40)),
            compiles=int(rng.integers(0, 1000)),
            draining=bool(rng.random() < 0.5),
            watchdog_trips=int(rng.integers(0, 10)),
            evictions=int(rng.integers(0, 10)),
            prefill_programs=int(rng.integers(0, 50)),
            cancelled_tokens=int(rng.integers(0, 2**40))),
        DrainFrame(),
        CancelFrame(rid=int(rng.integers(0, 2**62))),
        ResumeFrame(
            rid=int(rng.integers(0, 2**62)),
            prompt=toks(int(rng.integers(1, 33))),
            max_new_tokens=int(rng.integers(1, 512)),
            generated=toks(int(rng.integers(0, 32))),
            eos_token=(None if rng.random() < 0.5
                       else int(rng.integers(0, 1000))),
            stop_tokens=toks(int(rng.integers(0, 5))),
            deadline=(None if rng.random() < 0.5
                      else float(rng.random() * 100)),
            attempts=int(rng.integers(0, 5)),
            seed=(None if rng.random() < 0.5
                  else int(rng.integers(0, 2**31))),
            replica=int(rng.integers(-1, 8))),
        DrainDoneFrame(replica=int(rng.integers(0, 8)),
                       migrated=int(rng.integers(0, 64))),
    ]


FUZZ_SEEDS = (0, 1, 2, 3, 4, 5)


class TestCodecFuzz:
    @pytest.mark.parametrize("seed", FUZZ_SEEDS)
    def test_roundtrip_byte_exact(self, seed):
        import numpy as np
        rng = np.random.default_rng(seed)
        for frame in _fuzz_frames(rng):
            buf = encode(frame, None)
            back = decode(buf, None)
            assert back == frame, frame
            assert encode(back, None) == buf, (
                f"{type(frame).__name__}: re-encode is not byte-exact")

    @pytest.mark.parametrize("seed", FUZZ_SEEDS)
    def test_every_truncation_raises_truncated(self, seed):
        import numpy as np
        rng = np.random.default_rng(seed)
        for frame in _fuzz_frames(rng):
            buf = encode(frame, None)
            for cut in range(len(buf)):
                try:
                    with pytest.raises(TruncatedFrame):
                        decode(buf[:cut], None)
                except BaseException:
                    print(f"{type(frame).__name__} cut at {cut}/"
                          f"{len(buf)}")
                    raise

    @pytest.mark.parametrize("seed", FUZZ_SEEDS)
    def test_bit_flips_never_escape_raw(self, seed):
        import numpy as np
        rng = np.random.default_rng(seed)
        for frame in _fuzz_frames(rng):
            buf = bytearray(encode(frame, None))
            for _ in range(min(4 * len(buf), 256)):
                pos = int(rng.integers(0, len(buf)))
                bit = 1 << int(rng.integers(0, 8))
                mut = bytearray(buf)
                mut[pos] ^= bit
                try:
                    decode(bytes(mut), None)  # a legal mutation is fine
                except WireError:
                    pass  # the contract: WireError, with a message
                except BaseException as exc:  # pragma: no cover
                    raise AssertionError(
                        f"{type(frame).__name__}: flipping bit "
                        f"{bit:#x} at byte {pos} escaped as "
                        f"{type(exc).__name__}: {exc}") from exc

    @pytest.mark.parametrize("seed", FUZZ_SEEDS)
    def test_lying_length_fields_raise_wire_errors(self, seed):
        # corrupt every byte to 0xFF one at a time — covers every
        # length/count field with the nastiest value its width allows
        import numpy as np
        rng = np.random.default_rng(seed)
        for frame in _fuzz_frames(rng):
            buf = bytearray(encode(frame, None))
            for pos in range(len(buf)):
                mut = bytearray(buf)
                mut[pos] = 0xFF
                try:
                    decode(bytes(mut), None)
                except WireError:
                    pass
                except BaseException as exc:  # pragma: no cover
                    raise AssertionError(
                        f"{type(frame).__name__}: byte {pos}=0xFF "
                        f"escaped as {type(exc).__name__}: "
                        f"{exc}") from exc
