"""graftcheck — the fleet-plane model checker (analysis/fleet_check.py).

Three kinds of pin live here:

* the DEFAULT MATRIX is green and its visited-state counts sit inside
  a tolerance band — a silent 10x growth (a transition added without
  thinking about the cross product) or a silent 10x shrink (a guard
  accidentally strangling reachability) both fail loudly;
* every seeded protocol bug in the selfcheck fixture set is CAUGHT,
  and its counterexample schedule REPLAYS deterministically to the
  same invariant — the checker's sensitivity, pinned;
* bound overflow is reported (never silent), and partial-order
  reduction is an optimization, not a soundness lever: POR on/off
  reach the same verdict on small bounds.
"""

import time

import pytest

from akka_allreduce_tpu.analysis import fleet_model as fm
from akka_allreduce_tpu.analysis.fleet_check import (
    check_default_bounds,
    default_bounds_for,
    explore,
    replay,
    run_fleet_plane,
)
from akka_allreduce_tpu.analysis.selfcheck import FLEET_FIXTURES

# Pinned visited-state counts for the default lint matrix.  These move
# ONLY when the model changes — and then the new count belongs in the
# same commit, with the state-space delta argued in its message.
# PR 20 (elastic fleet): +66% at th=1, +64% at th=2 — the scale_in /
# rollout_drain / rollout_up / rollout_probe transitions and the
# per-replica rolling+ckpt bits, with deterministic victim choice
# (highest-index scale-in, ascending rollout) keeping the product
# linear rather than combinatorial.
PINNED_VISITED = {1: 275_080, 2: 87_774}
TOLERANCE = 0.10  # +-10%: canonicalization tweaks, not silent blowups


@pytest.fixture(scope="module")
def matrix():
    t0 = time.process_time()
    results = check_default_bounds()
    return results, time.process_time() - t0


class TestDefaultMatrix:
    def test_green_and_complete(self, matrix):
        results, _ = matrix
        for th, res in results.items():
            assert res.violation is None, (
                f"th={th}: {res.violation.invariant}: "
                f"{res.violation.message}")
            assert res.overflow is None, (
                f"th={th}: overflow {res.overflow} at {res.visited} "
                f"states — the default bounds no longer fit the budget")
            assert res.quiescent > 0, f"th={th}: no quiescent states?"

    @pytest.mark.parametrize("th", sorted(PINNED_VISITED))
    def test_visited_count_pinned(self, matrix, th):
        results, _ = matrix
        pin = PINNED_VISITED[th]
        got = results[th].visited
        lo, hi = int(pin * (1 - TOLERANCE)), int(pin * (1 + TOLERANCE))
        assert lo <= got <= hi, (
            f"th={th}: visited {got} outside [{lo}, {hi}] (pin {pin}) "
            f"— the model's state space moved; re-pin in the same "
            f"commit with the delta argued")

    def test_cpu_budget(self, matrix):
        _, cpu = matrix
        assert cpu < 60.0, (
            f"default matrix took {cpu:.1f}s CPU — over the 60s lint "
            f"budget; shrink bounds or strengthen dedup")

    def test_plane_findings_report_counts(self, matrix):
        del matrix  # ordering only: reuse warmed CPU, fresh run here
        findings, names = run_fleet_plane(
            bounds=fm.DEFAULT_BOUNDS._replace(
                spares=0, fault_budget=1, requests=2),
            th_values=(1,))
        assert names == ["fleet:th=1"]
        (f,) = findings
        assert f.severity == "info"
        assert "all invariants hold over" in f.message
        assert "visited" in f.where


class TestSeededBugs:
    @pytest.mark.parametrize(
        "name,bug,expect_inv,bkw",
        [(n, b, e, k) for n, _, b, e, k in FLEET_FIXTURES],
        ids=[n for n, *_ in FLEET_FIXTURES])
    def test_bug_caught_and_replays(self, name, bug, expect_inv, bkw):
        bounds = fm.DEFAULT_BOUNDS._replace(**bkw)
        res = explore(bounds, bugs=frozenset({bug}))
        assert res.violation is not None, (
            f"{name}: checker is blind to seeded bug '{bug}'")
        v = res.violation
        assert v.invariant == expect_inv, (
            f"{name}: caught as '{v.invariant}', pinned "
            f"'{expect_inv}'")
        # the counterexample is a first-class artifact: it must replay
        _, bad = replay(bounds, v.schedule, bugs=frozenset({bug}))
        assert any(inv == expect_inv for inv, _ in bad), (
            f"{name}: pinned schedule no longer reproduces "
            f"{expect_inv}: {bad}")

    def test_clean_model_has_no_violation_at_fixture_bounds(self):
        # the fixtures' shrunk bounds are themselves green without bugs
        # (otherwise 'caught' would be vacuous)
        for name, _, _, _, bkw in FLEET_FIXTURES:
            bounds = fm.DEFAULT_BOUNDS._replace(**bkw)
            res = explore(bounds)
            assert res.violation is None, (
                f"{name}: fixture bounds are not clean without the "
                f"bug: {res.violation}")


class TestBoundsAndSoundness:
    def test_overflow_reported_never_silent(self):
        res = explore(fm.DEFAULT_BOUNDS._replace(max_states=50))
        assert res.overflow == "states"
        findings, _ = run_fleet_plane(
            bounds=fm.DEFAULT_BOUNDS._replace(max_states=50),
            th_values=(1,))
        (f,) = findings
        assert f.severity == "error"
        assert "INCOMPLETE" in f.message

    def test_por_is_verdict_preserving(self):
        # POR prunes interleavings, not reachable violations: on small
        # bounds both modes agree on the verdict, clean and buggy
        small = fm.DEFAULT_BOUNDS._replace(
            replicas=2, spares=0, requests=2, fault_budget=1,
            max_states=200_000)
        a = explore(small, por=True)
        b = explore(small, por=False)
        assert (a.violation is None) == (b.violation is None)
        assert a.quiescent == b.quiescent  # same terminal behaviors
        assert a.visited <= b.visited  # POR only ever prunes

        bug = frozenset({"restart_no_inc_bump"})
        a = explore(small, bugs=bug, por=True)
        b = explore(small, bugs=bug, por=False)
        assert a.violation is not None and b.violation is not None
        assert a.violation.invariant == b.violation.invariant

    def test_replay_rejects_drifted_schedule(self):
        bounds = fm.DEFAULT_BOUNDS._replace(
            spares=0, fault_budget=1, requests=2)
        with pytest.raises(AssertionError, match="not enabled"):
            # a schedule whose first step can't fire from the initial
            # state: completing a request that was never dispatched
            replay(bounds, (("complete", 0, 0),))
