"""Canonical-scale payloads across the real cross-process wire.

Round-4 verdict #3: the all-native cluster (C++ engines + framed TCP
transport, OS process per worker — the deployment shape of the
reference's netty remoting, reference: application.conf:5-11) had only
ever carried 778 floats. This pins a >=1M-element payload crossing real
process boundaries with the sink's exactness contract intact: every
worker asserts ``output == N x input`` (ThroughputSink semantics,
reference: AllreduceWorker.scala:329-343) and exits nonzero otherwise.
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_megascale_payload_crosses_real_wire():
    """4 OS worker processes x 1,048,576 f32 (4 MiB payload/round), all
    engines C++, loopback TCP: rounds complete and every worker's sink
    asserts output == 4 x input at checkpoint cadence."""
    from akka_allreduce_tpu.config import (AllreduceConfig, DataConfig,
                                           ThresholdConfig, WorkerConfig)
    from akka_allreduce_tpu.native import build_library
    from akka_allreduce_tpu.protocol.remote import (free_port,
                                                    run_master_native)

    build_library()  # before the workers race to build it
    port = free_port()
    workers, elems, rounds = 4, 1_048_576, 6
    config = AllreduceConfig(
        thresholds=ThresholdConfig(1.0, 1.0, 1.0),
        data=DataConfig(data_size=elems, max_chunk_size=16_384,
                        max_round=rounds),
        workers=WorkerConfig(total_size=workers, max_lag=1))
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    code = (
        "import sys\n"
        "from akka_allreduce_tpu.protocol.remote import "
        "run_worker_native\n"
        f"n = run_worker_native(master_port={port}, checkpoint=2, "
        f"assert_multiple={workers}, timeout_s=240)\n"
        "sys.exit(0 if n > 0 else 4)\n")
    procs = [subprocess.Popen([sys.executable, "-c", code], env=env,
                              cwd=ROOT) for _ in range(workers)]
    try:
        # wide liveness window: 5 CPU-bound processes on a 1-core box
        # can starve a worker of scheduling past the 10 s default
        got, stamps = run_master_native(config, port=port, timeout_s=240,
                                        unreachable_after_s=120.0,
                                        with_round_times=True)
        rcs = [p.wait(timeout=90) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert got == rounds, f"master completed {got}/{rounds} rounds"
    assert len(stamps) == rounds and all(
        b >= a for a, b in zip(stamps, stamps[1:]))
    # exit 0 == the C++ sink verified output == 4 x input every
    # checkpoint AND flushed outputs; 4 == ran but flushed nothing
    assert rcs == [0] * workers, f"worker exit codes {rcs}"
