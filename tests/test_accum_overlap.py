"""Grad-accum overlap schedule (TrainConfig.accum_schedule="overlap").

The contract (ISSUE 1 acceptance): syncing each microbatch's gradients
as produced — double-buffered through the scan carry so the collective
overlaps the next microbatch's compute — produces step-for-step
identical losses to the deferred single-sync path for the f32 transport
(sum-of-psums vs psum-of-sums: only f32 summation order differs).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from akka_allreduce_tpu.models.train import (
    TrainConfig,
    make_grad_step,
    make_train_state,
    make_train_step,
)
from akka_allreduce_tpu.models.transformer import TransformerConfig
from akka_allreduce_tpu.parallel.mesh import MeshSpec, make_device_mesh

MCFG = TransformerConfig(vocab_size=41, d_model=32, n_heads=4, n_layers=1,
                         d_ff=64, max_seq=16)


def tokens(seed=3, b=8, t=16):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, 41, size=(b, t), dtype=np.int32))


def base_cfg(**kw):
    return TrainConfig(model=MCFG, bucket_elems=256, grad_axes=("dp",),
                       grad_accum=4, **kw)


class TestOverlapIdentity:
    def test_losses_match_deferred_step_for_step(self):
        """The acceptance regression: a short f32 training run under
        each schedule, loss compared per step."""
        mesh = make_device_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
        losses = {}
        for sched in ("deferred", "overlap"):
            cfg = base_cfg(accum_schedule=sched)
            params, opt_state, opt = make_train_state(jax.random.key(0),
                                                      cfg, mesh)
            step = make_train_step(cfg, mesh, opt)
            ls = []
            for i in range(5):
                params, opt_state, m = step(params, opt_state, tokens(i))
                ls.append(float(m["loss"]))
            losses[sched] = ls
        np.testing.assert_allclose(losses["overlap"], losses["deferred"],
                                   rtol=1e-5, atol=1e-6)

    def test_synced_grads_match_deferred(self):
        mesh = make_device_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
        cfg_d = base_cfg()
        cfg_o = base_cfg(accum_schedule="overlap")
        params, _, _ = make_train_state(jax.random.key(0), cfg_d, mesh)
        gd, md = jax.jit(make_grad_step(cfg_d, mesh))(params, tokens(), 7)
        go, mo = jax.jit(make_grad_step(cfg_o, mesh))(params, tokens(), 7)
        assert float(md["loss"]) == pytest.approx(float(mo["loss"]),
                                                  rel=1e-6)
        assert int(md["min_bucket_count"]) == int(mo["min_bucket_count"])
        for (path, a), b in zip(jax.tree.flatten_with_path(gd)[0],
                                jax.tree.leaves(go)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7,
                                       err_msg=str(path))

    def test_composes_with_windowed_transport(self):
        """overlap x windowed: per-microbatch syncs each internally
        pipelined — both overlap layers at once — still the deferred
        fused gradients (windowing is bitwise, overlap reorders sums)."""
        mesh = make_device_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
        cfg_d = base_cfg()
        cfg_ow = base_cfg(accum_schedule="overlap",
                          transport_schedule="windowed", num_windows=2)
        params, _, _ = make_train_state(jax.random.key(0), cfg_d, mesh)
        gd, _ = jax.jit(make_grad_step(cfg_d, mesh))(params, tokens(), 7)
        go, _ = jax.jit(make_grad_step(cfg_ow, mesh))(params, tokens(), 7)
        for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(go)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)

    def test_unknown_schedule_rejected(self):
        mesh = make_device_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
        cfg = base_cfg(accum_schedule="eager")
        with pytest.raises(ValueError, match="accum_schedule"):
            make_grad_step(cfg, mesh)


@pytest.mark.slow
class TestOverlapComposition:
    def test_int8_wire_overlap_still_trains(self):
        """overlap + int8: K quantized syncs per step, per-microbatch
        rounding keys. Exactness is not claimed (each sync rounds);
        the pin is the same as the deferred int8 composition test —
        finite, decreasing losses."""
        mesh = make_device_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
        cfg = TrainConfig(model=MCFG, bucket_elems=256, grad_axes=("dp",),
                          grad_accum=2, accum_schedule="overlap",
                          grad_transport="int8", learning_rate=5e-3)
        params, opt_state, opt = make_train_state(jax.random.key(1), cfg,
                                                  mesh)
        step = make_train_step(cfg, mesh, opt)
        losses = []
        for _ in range(8):
            params, opt_state, m = step(params, opt_state, tokens(8))
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses

    def test_masked_overlap_counts_honest(self):
        """overlap + dynamic valid mask: per-bucket counts identical to
        the deferred masked path (the mask is per-round, so every
        microbatch sync sees the same counts)."""
        mesh = make_device_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
        cfg_d = base_cfg()
        cfg_o = base_cfg(accum_schedule="overlap")
        params, _, _ = make_train_state(jax.random.key(0), cfg_d, mesh)
        from akka_allreduce_tpu.models.train import dense_bucket_count
        nb = dense_bucket_count(cfg_d, mesh, params)
        valid = np.ones((2, nb), np.float32)
        valid[1, 0] = 0.0  # rank 1 misses bucket 0 this round
        gd = make_grad_step(cfg_d, mesh, dynamic_valid=True)
        go = make_grad_step(cfg_o, mesh, dynamic_valid=True)
        _, md = gd(params, tokens(), 7, valid=valid)
        grads_o, mo = go(params, tokens(), 7, valid=valid)
        assert int(md["min_bucket_count"]) == 1
        assert int(mo["min_bucket_count"]) == 1
        assert all(np.isfinite(np.asarray(g)).all()
                   for g in jax.tree.leaves(grads_o))
