"""Compiled-HLO lint plane tests (analysis/hlo.py, ISSUE 14).

Three layers, mirroring the plane's own structure:

* **Golden-module parser tests** — small hand-pinned HLO snippets pin
  exactly the facts the passes consume: the ``input_output_alias``
  table (tuple output indices, param indices, alias kinds), async
  ``-start``/``-done`` pair matching with the compute-between count,
  the generic ``async-start`` wrapper resolution, the fusion census,
  and the collective census/ordering. A parser that bit-rots against
  the dialect fails here, on a 20-line snippet, not inside a 479 kB
  train-step module.
* **Fixture coverage** — every HLO selfcheck fixture must be caught by
  its pass AND be provably invisible to the jaxpr/StableHLO catalog
  (the plane's existence proof), plus the donation-dedupe contract:
  one dropped donation is ONE finding when both planes run.
* **Lint-clean pins** — all 22 catalog entries stay clean with the
  HLO passes armed (train entries under the ``slow`` marker, matching
  test_analysis.py's split; the full catalog runs in CI via
  ``lint --all --hlo --strict``).
"""

import jax
import jax.numpy as jnp
import pytest

from akka_allreduce_tpu.analysis.core import run_passes
from akka_allreduce_tpu.analysis.hlo import (
    HloPolicy,
    expected_swing_census,
    parse_hlo_text,
    run_hlo_passes,
    run_with_hlo,
)
from akka_allreduce_tpu.analysis.selfcheck import (
    HLO_FIXTURES,
    fixture_hlo_dropped_alias,
)

# -- golden modules -----------------------------------------------------

GOLDEN_SYNC = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {1}, must-alias) }, entry_computation_layout={(f32[8,64]{1,0})->f32[8,64]{1,0}}, num_partitions=4

%region_0 (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add.0 = f32[] add(f32[] %a, f32[] %b)
}

%fused_computation (param_0: f32[8,64]) -> f32[8,64] {
  %param_0 = f32[8,64]{1,0} parameter(0)
  %constant.1 = f32[] constant(2)
  %broadcast.1 = f32[8,64]{1,0} broadcast(f32[] %constant.1), dimensions={}
  ROOT %multiply.1 = f32[8,64]{1,0} multiply(f32[8,64]{1,0} %param_0, f32[8,64]{1,0} %broadcast.1)
}

ENTRY %main.7_spmd (Arg_0.1: f32[8,64], Arg_1.2: f32[8,64]) -> f32[8,64] {
  %Arg_0.1 = f32[8,64]{1,0} parameter(0), metadata={op_name="state"}
  %reduce-scatter.1 = f32[8,16]{1,0} reduce-scatter(f32[8,64]{1,0} %Arg_0.1), channel_id=1, replica_groups={{0,1,2,3}}, dimensions={1}, to_apply=%region_0
  %fusion.1 = f32[8,64]{1,0} fusion(f32[8,64]{1,0} %Arg_0.1), kind=kLoop, calls=%fused_computation
  %all-gather.1 = f32[8,64]{1,0} all-gather(f32[8,16]{1,0} %reduce-scatter.1), channel_id=2, replica_groups={{0,1,2,3}}, dimensions={1}
  ROOT %add.1 = f32[8,64]{1,0} add(f32[8,64]{1,0} %fusion.1, f32[8,64]{1,0} %all-gather.1)
}
"""

GOLDEN_ASYNC = """\
HloModule async_mod, is_scheduled=true

ENTRY %main (p0: f32[8,64], p1: f32[8,64]) -> f32[8,128] {
  %p0 = f32[8,64]{1,0} parameter(0)
  %p1 = f32[8,64]{1,0} parameter(1)
  %ag-start.1 = (f32[8,64]{1,0}, f32[8,128]{1,0}) all-gather-start(f32[8,64]{1,0} %p0), channel_id=1, replica_groups={{0,1}}, dimensions={1}
  %dot.1 = f32[8,64]{1,0} dot(f32[8,64]{1,0} %p1, f32[8,64]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %ag-done.1 = f32[8,128]{1,0} all-gather-done((f32[8,64]{1,0}, f32[8,128]{1,0}) %ag-start.1), channel_id=1
  ROOT %concatenate.1 = f32[8,128]{1,0} concatenate(f32[8,128]{1,0} %ag-done.1, f32[8,64]{1,0} %dot.1), dimensions={1}
}
"""

GOLDEN_GENERIC_ASYNC = """\
HloModule generic_async, is_scheduled=true

%sum (x: f32[], y: f32[]) -> f32[] {
  %x = f32[] parameter(0)
  %y = f32[] parameter(1)
  ROOT %add.2 = f32[] add(f32[] %x, f32[] %y)
}

%ar_comp (a: f32[64]) -> f32[64] {
  %a = f32[64]{0} parameter(0)
  ROOT %all-reduce.9 = f32[64]{0} all-reduce(f32[64]{0} %a), channel_id=3, replica_groups={{0,1}}, to_apply=%sum
}

ENTRY %main (p: f32[64]) -> f32[64] {
  %p = f32[64]{0} parameter(0)
  %as.1 = ((f32[64]), f32[64]) async-start(f32[64]{0} %p), calls=%ar_comp
  %exp.3 = f32[64]{0} exponential(f32[64]{0} %p)
  ROOT %ad.1 = f32[64]{0} async-done(((f32[64]), f32[64]) %as.1), calls=%ar_comp
}
"""

GOLDEN_UNORDERED = """\
HloModule unordered, is_scheduled=true

ENTRY %main (p: f32[8,64]) -> f32[8,64] {
  %p = f32[8,64]{1,0} parameter(0)
  %all-gather.1 = f32[8,64]{1,0} all-gather(f32[8,64]{1,0} %p), channel_id=1, replica_groups={{0,1}}, dimensions={1}
  ROOT %reduce-scatter.1 = f32[8,64]{1,0} reduce-scatter(f32[8,64]{1,0} %all-gather.1), channel_id=2, replica_groups={{0,1}}, dimensions={1}, to_apply=%sum
}
"""


class TestParser:
    def test_module_header_and_alias_table(self):
        m = parse_hlo_text(GOLDEN_SYNC)
        assert m.name == "jit_step"
        assert m.attrs.get("num_partitions") == "4"
        assert len(m.aliases) == 2
        a0, a1 = m.aliases
        assert a0.output_index == (0,)
        assert a0.param_number == 0 and a0.param_index == ()
        assert a0.kind == "may-alias"
        assert a1.output_index == (1,)
        assert a1.param_number == 2 and a1.param_index == (1,)
        assert a1.kind == "must-alias"
        assert m.aliased_params == {0, 2}

    def test_whole_result_alias_entry(self):
        # single-output modules alias with an EMPTY output index tuple
        header = ("HloModule m, is_scheduled=true, "
                  "input_output_alias={ {}: (0, {}, may-alias) }\n\n"
                  "ENTRY %main (p: f32[4]) -> f32[4] {\n"
                  "  ROOT %p = f32[4]{0} parameter(0)\n}\n")
        m = parse_hlo_text(header)
        assert len(m.aliases) == 1
        assert m.aliases[0].output_index == ()
        assert m.aliased_params == {0}

    def test_computations_instructions_operands(self):
        m = parse_hlo_text(GOLDEN_SYNC)
        assert set(m.computations) == {"region_0", "fused_computation",
                                       "main.7_spmd"}
        assert m.entry == "main.7_spmd"
        entry = m.computations[m.entry]
        ag = entry.find("all-gather.1")
        assert ag is not None
        assert ag.opcode == "all-gather"
        assert ag.dtype == "f32" and ag.shape == (8, 64)
        assert ag.operands == ("reduce-scatter.1",)
        assert ag.attrs["channel_id"] == "2"
        root = entry.find("add.1")
        assert root.is_root
        assert set(root.operands) == {"fusion.1", "all-gather.1"}

    def test_fusion_census_and_called_comps(self):
        m = parse_hlo_text(GOLDEN_SYNC)
        assert m.fusion_census() == {"kLoop": 1}
        assert m.fusion_computations == {"fused_computation"}

    def test_collective_census_sync(self):
        m = parse_hlo_text(GOLDEN_SYNC)
        assert m.collective_census() == {"reduce-scatter": 1,
                                         "all-gather": 1}
        assert m.async_pairs() == []

    def test_async_pair_matching_counts_compute(self):
        m = parse_hlo_text(GOLDEN_ASYNC)
        # start counted once; census sees ONE logical all-gather
        assert m.collective_census() == {"all-gather": 1}
        pairs = m.async_pairs()
        assert len(pairs) == 1
        start, done, between = pairs[0]
        assert start.name == "ag-start.1" and done.name == "ag-done.1"
        assert between == 1  # the dot, and only the dot

    def test_generic_async_wrapper_resolves_and_counts_once(self):
        m = parse_hlo_text(GOLDEN_GENERIC_ASYNC)
        # the wrapped all-reduce must count ONCE (the wrapper), not
        # twice (wrapper + body)
        assert m.collective_census() == {"all-reduce": 1}
        pairs = m.async_pairs()
        assert len(pairs) == 1
        start, done, between = pairs[0]
        assert start.name == "as.1" and done.name == "ad.1"
        assert between == 1  # the exponential

    def test_tuple_result_shape(self):
        m = parse_hlo_text(GOLDEN_ASYNC)
        start = m.computations["main"].find("ag-start.1")
        # tuple results report the first array element
        assert start.dtype == "f32" and start.shape == (8, 64)

    def test_percentless_operand_dialect_still_parses_edges(self):
        # a printer that drops the % sigil must not silently empty the
        # operand edges (async done-matching and the dequantize lookup
        # walk them) — the fallback takes the last non-shape token
        text = GOLDEN_ASYNC.replace("%ag-start.1)", "ag-start.1)") \
                           .replace("%p0)", "p0)")
        m = parse_hlo_text(text)
        done = m.computations["main"].find("ag-done.1")
        assert done.operands == ("ag-start.1",)
        pairs = m.async_pairs()
        assert len(pairs) == 1 and pairs[0][2] == 1
        # literal operands (parameter indices) stay OUT of the edges
        start = m.computations["main"].find("ag-start.1")
        assert start.operands == ("p0",)

    def test_long_entry_signature_with_index_comments(self):
        # real entry signatures wrap hundreds of params with
        # /*index=N*/ comments — the header must still parse (the bug
        # the train-step calibration caught)
        text = ("HloModule big, is_scheduled=true\n\n"
                "ENTRY %main (p0: f32[4], /*index=1*/p1: f32[4]) "
                "-> f32[4] {\n"
                "  %p0 = f32[4]{0} parameter(0)\n"
                "  %p1 = f32[4]{0} parameter(1)\n"
                "  ROOT %add.9 = f32[4]{0} add(f32[4]{0} %p0, "
                "f32[4]{0} %p1)\n}\n")
        m = parse_hlo_text(text)
        assert m.entry == "main"
        assert len(m.computations["main"].instructions) == 3


class TestHloPassesOnGoldens:
    def _ctx(self, text, policy):
        ctx = fixture_hlo_dropped_alias()  # any traced ctx chassis
        ctx._hlo_text = text
        ctx.hlo_policy = policy
        ctx.donated = ()  # neutralize aliasing for census-only goldens
        return ctx

    def test_census_pass_clean_and_dirty(self):
        ctx = self._ctx(GOLDEN_SYNC, HloPolicy(
            census={"reduce-scatter": 1, "all-gather": 1},
            pair_rs_ag=True, overlap="off"))
        assert not [f for f in run_hlo_passes(ctx)
                    if f.severity == "error"]
        ctx = self._ctx(GOLDEN_SYNC, HloPolicy(
            census={"all-reduce": 1}, overlap="off"))
        errs = [f for f in run_hlo_passes(ctx)
                if f.pass_name == "hlo-census"]
        # all-reduce missing (0 != 1) + rs/ag unexpected (census is
        # exhaustive)
        assert len(errs) == 3, [f.message for f in errs]

    def test_ordering_violation(self):
        ctx = self._ctx(GOLDEN_UNORDERED, HloPolicy(
            census={"reduce-scatter": 1, "all-gather": 1},
            pair_rs_ag=True, overlap="off"))
        errs = [f for f in run_hlo_passes(ctx)
                if f.pass_name == "hlo-census"]
        assert errs and "before reduce-scatter" in errs[0].message

    def test_overlap_pass_accepts_real_async(self):
        ctx = self._ctx(GOLDEN_ASYNC, HloPolicy(overlap="require",
                                                census=None))
        assert not [f for f in run_hlo_passes(ctx)
                    if f.pass_name == "hlo-overlap"]

    def test_require_flags_partially_split_module(self):
        # a module where the flags split SOME collectives but left one
        # sync: the leftover sync transfer still serializes — under
        # "require" that is an error, pairs or no pairs
        partial = GOLDEN_ASYNC.replace(
            "ROOT %concatenate.1 = f32[8,128]{1,0} concatenate("
            "f32[8,128]{1,0} %ag-done.1, f32[8,64]{1,0} %dot.1), "
            "dimensions={1}",
            "%all-reduce.7 = f32[8,64]{1,0} all-reduce(f32[8,64]{1,0} "
            "%dot.1), channel_id=9, replica_groups={{0,1}}, "
            "to_apply=%sum\n"
            "  ROOT %concatenate.1 = f32[8,128]{1,0} concatenate("
            "f32[8,128]{1,0} %ag-done.1, f32[8,64]{1,0} "
            "%all-reduce.7), dimensions={1}")
        ctx = self._ctx(partial, HloPolicy(overlap="require",
                                           census=None))
        errs = [f for f in run_hlo_passes(ctx)
                if f.pass_name == "hlo-overlap"
                and f.severity == "error"]
        assert errs and "alongside 1 async pair" in errs[0].message, \
            [f.message for f in run_hlo_passes(ctx)]

    def test_swing_census_helper(self):
        assert expected_swing_census(8) == {"collective-permute": 3}
        assert expected_swing_census(4, wire_collectives=2) == \
            {"collective-permute": 4}


class TestHloFixturesCaught:
    """The plane's existence proof, test-side: each fixture is (a)
    provably invisible to the jaxpr/StableHLO catalog and (b) caught
    by its HLO pass at the expected severity."""

    @pytest.mark.parametrize("name,build,expect_pass,expect_sev",
                             HLO_FIXTURES,
                             ids=[f[0] for f in HLO_FIXTURES])
    def test_jaxpr_quiet_hlo_fires(self, name, build, expect_pass,
                                   expect_sev):
        ctx = build()
        base = [f for f in run_passes(ctx)
                if f.severity in ("error", "warning")]
        assert not base, (
            f"{name} must be a bug the base catalog cannot see, got "
            f"{[(f.pass_name, f.message) for f in base]}")
        hits = [f for f in run_hlo_passes(ctx)
                if f.pass_name == expect_pass
                and f.severity == expect_sev]
        assert hits, [(f.pass_name, f.severity)
                      for f in run_hlo_passes(ctx)]


class TestDonationDedupe:
    """ISSUE 14 satellite: one dropped donation is ONE finding when
    both planes run, named with both the marker and the missing-alias
    evidence — and the StableHLO pass still audits alone when the HLO
    plane is off."""

    def test_both_planes_one_finding_with_both_evidences(self):
        ctx = fixture_hlo_dropped_alias()
        findings = run_with_hlo(ctx)
        drops = [f for f in findings
                 if "alias" in f.message or "survive" in f.message]
        assert len(drops) == 1, [(f.pass_name, f.message)
                                 for f in drops]
        f = drops[0]
        assert f.pass_name == "hlo-aliasing"
        # both evidences in the one message: the marker survived
        # StableHLO, the compiled alias entry is missing
        assert "marker survived" in f.message
        assert "input_output_alias" in f.message
        # per-parameter naming
        assert f.where == "arg0"

    def test_stablehlo_pass_still_audits_alone(self):
        from akka_allreduce_tpu.analysis.selfcheck import (
            fixture_dropped_donation)
        ctx = fixture_dropped_donation()
        assert not ctx.hlo_armed
        drops = [f for f in run_passes(ctx)
                 if f.pass_name == "donation"
                 and "did not survive lowering" in f.message]
        assert len(drops) == 1

    def test_armed_ctx_defers_stablehlo_audit(self):
        from akka_allreduce_tpu.analysis.selfcheck import (
            fixture_dropped_donation)
        ctx = fixture_dropped_donation()
        ctx.hlo_armed = True
        drops = [f for f in run_passes(ctx)
                 if f.pass_name == "donation"
                 and "did not survive" in f.message]
        assert not drops  # the HLO plane owns the audit now

    def test_no_policy_entry_keeps_stablehlo_audit_under_hlo(self):
        """The deferral must NOT fire for entries the hlo-aliasing
        pass will never visit: a context without an hlo_policy run
        through run_with_hlo still gets its StableHLO donation audit —
        otherwise `--hlo` (the STRICTER mode) would silently drop the
        donation check for exactly those entries."""
        from akka_allreduce_tpu.analysis.selfcheck import (
            fixture_dropped_donation)
        ctx = fixture_dropped_donation()
        assert ctx.hlo_policy is None
        drops = [f for f in run_with_hlo(ctx)
                 if f.pass_name == "donation"
                 and "did not survive" in f.message]
        assert len(drops) == 1
        assert not ctx.hlo_armed

    def test_check_aliasing_off_keeps_stablehlo_audit(self):
        """HloPolicy(check_aliasing=False) likewise leaves the
        StableHLO audit in place — deferring to a disabled pass is a
        dropped check, not a dedupe."""
        from akka_allreduce_tpu.analysis.selfcheck import (
            fixture_dropped_donation)
        ctx = fixture_dropped_donation()
        ctx.hlo_policy = HloPolicy(check_aliasing=False, census=None,
                                   fusion_census=False)
        ctx._hlo_text = "HloModule stub\n"
        drops = [f for f in run_with_hlo(ctx)
                 if f.pass_name == "donation"
                 and "did not survive" in f.message]
        assert len(drops) == 1


_FAST_TARGETS = [
    "generate", "engine_step", "engine_multi_step",
    "engine_paged_step", "engine_prefill", "engine_recovery",
    "engine_step_telemetry", "engine_speculative_step",
    "collective_fused", "collective_windowed", "collective_int8",
    "collective_bf16", "collectives_swing", "collectives_ef8",
    "collectives_hierarchical", "collective_auto",
]
_TRAIN_TARGETS = [
    "train_step", "train_step_windowed", "train_step_int8",
    "train_step_bf16", "train_step_pp", "train_step_moe",
]


def _hlo_gating(target):
    from akka_allreduce_tpu.analysis.entrypoints import ENTRYPOINTS
    ctx = ENTRYPOINTS[target]()
    findings = run_with_hlo(ctx)
    return [f for f in findings if f.severity in ("error", "warning")]


class TestCleanEntrypointsHloClean:
    """Lint-clean pins with the COMPILED-module catalog armed: the 22
    entries' alias tables, collective censuses, and fusion boundaries
    are now regression gates, not just the jaxprs (the ``lint --all
    --hlo --strict`` acceptance, test-side)."""

    @pytest.mark.parametrize("target", _FAST_TARGETS)
    def test_fast_entrypoints_hlo_clean(self, target):
        gating = _hlo_gating(target)
        assert not gating, [f"[{f.pass_name}] {f.message}"
                            for f in gating]

    @pytest.mark.slow
    @pytest.mark.parametrize("target", _TRAIN_TARGETS)
    def test_train_entrypoints_hlo_clean(self, target):
        gating = _hlo_gating(target)
        assert not gating, [f"[{f.pass_name}] {f.message}"
                            for f in gating]

    def test_every_entry_carries_an_hlo_policy(self):
        """All 22 catalog entries opted into the compiled-module plane
        — an entry added without an hlo_policy silently skips the HLO
        passes, which this pin turns into a visible failure."""
        from akka_allreduce_tpu.analysis import entrypoints as ep
        import inspect
        # static check: every builder wires hlo_policy (building all
        # 22 here would re-trace the world; the clean pins above and
        # the CI lint run cover behavior)
        src = inspect.getsource(ep)
        assert len(ep.ENTRYPOINTS) == 22
        assert src.count("hlo_policy=") >= len(ep.ENTRYPOINTS)

    def test_engine_census_is_exhaustive_empty(self):
        """The serving engine's compiled module must carry NO
        collectives — census {} is the claim that no mesh axis leaks
        into the single-host hot path, checked on the module."""
        from akka_allreduce_tpu.analysis.entrypoints import ENTRYPOINTS
        ctx = ENTRYPOINTS["engine_step"]()
        module = parse_hlo_text(ctx.hlo)
        assert module.collective_census() == {}
        # and the alias table kept every donated buffer
        declared = [i for i, d in enumerate(ctx.donated) if d]
        assert declared
        assert set(declared) <= module.aliased_params

    def test_collective_auto_module_is_the_plan(self):
        """The HLO half of PR 13's plan-conformance contract: under the
        frozen swing plan the COMPILED module carries exactly 2
        collective-permutes (log2(2) hop x values+scales), 1 exact
        all-reduce, and no two-phase ops at all."""
        from akka_allreduce_tpu.analysis.entrypoints import ENTRYPOINTS
        ctx = ENTRYPOINTS["collective_auto"]()
        module = parse_hlo_text(ctx.hlo)
        assert module.collective_census() == {
            "collective-permute": 2, "all-reduce": 1}


class TestLazyCompile:
    def test_hlo_is_lazy_and_cached(self):
        from akka_allreduce_tpu.analysis.entrypoints import ENTRYPOINTS
        ctx = ENTRYPOINTS["collectives_swing"]()
        assert ctx._hlo_text is None  # nothing compiled at trace time
        first = ctx.hlo
        assert first is not None and "HloModule" in first
        assert ctx.hlo is first  # cached, not recompiled

    def test_entry_without_policy_skips_hlo_passes(self):
        from akka_allreduce_tpu.analysis.core import (LintPolicy,
                                                      trace_entry)

        def entry(x):
            return x + 1

        ctx = trace_entry("no_policy", entry,
                          (jnp.zeros((4,), jnp.float32),),
                          LintPolicy(), lower=False)
        assert run_hlo_passes(ctx) == []
        assert ctx._hlo_text is None  # and nothing compiled
        # a policy-less context carries NO thunk at all: a stray
        # ctx.hlo read can never trigger a surprise compile
        assert ctx._hlo_thunk is None
        assert ctx.hlo is None
