"""GPT-2-style weight tying: the output head IS the input embedding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from akka_allreduce_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_apply,
)

TCFG = TransformerConfig(vocab_size=53, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_seq=48, tie_embeddings=True)


def toks(b=2, t=48, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, 53, size=(b, t), dtype=np.int32))


class TestTying:
    def test_no_lm_head_param(self):
        params = init_transformer(jax.random.key(0), TCFG)
        assert "lm_head" not in params
        n_tied = sum(x.size for x in jax.tree.leaves(params))
        untied = init_transformer(
            jax.random.key(0),
            TransformerConfig(**{**TCFG.__dict__, "tie_embeddings": False}))
        n_untied = sum(x.size for x in jax.tree.leaves(untied))
        assert n_untied - n_tied == 53 * 32  # exactly the vocab matrix

    @pytest.mark.slow
    def test_logits_use_transposed_embedding(self):
        params = init_transformer(jax.random.key(1), TCFG)
        out = transformer_apply(params, toks(), TCFG)
        # splice the embedding in as an explicit lm_head in an untied
        # config: outputs must be identical
        untied_cfg = TransformerConfig(
            **{**TCFG.__dict__, "tie_embeddings": False})
        spliced = dict(params, lm_head=params["embed"].T)
        np.testing.assert_allclose(
            np.asarray(transformer_apply(spliced, toks(), untied_cfg)),
            np.asarray(out), atol=1e-6)

    @pytest.mark.slow
    def test_gradient_flows_from_both_ends(self):
        """The tied matrix receives gradient from the input gather AND
        the output matmul — its grad must differ from the untied embed
        grad on identical data."""
        from akka_allreduce_tpu.models.transformer import next_token_loss

        def gembed(cfg):
            params = init_transformer(jax.random.key(2), cfg)
            if not cfg.tie_embeddings:
                params["lm_head"] = params["embed"].T  # same math
            def loss(p):
                s, w = next_token_loss(p, toks(), cfg)
                return s / w
            return jax.grad(loss)(params)["embed"]

        untied_cfg = TransformerConfig(
            **{**TCFG.__dict__, "tie_embeddings": False})
        g_tied = gembed(TCFG)
        g_untied = gembed(untied_cfg)
        # tied grad = untied embed grad + head grad^T; they must differ
        assert float(jnp.abs(g_tied - g_untied).max()) > 1e-4

    @pytest.mark.slow
    def test_train_step_learns(self):
        from akka_allreduce_tpu.models.train import (
            TrainConfig, make_train_state, make_train_step)
        from akka_allreduce_tpu.parallel.mesh import (MeshSpec,
                                                      make_device_mesh)
        mesh = make_device_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
        cfg = TrainConfig(model=TCFG, learning_rate=1e-2, bucket_elems=256,
                          grad_axes=("dp",))
        params, opt_state, opt = make_train_state(jax.random.key(3), cfg,
                                                  mesh)
        assert "lm_head" not in params
        step = make_train_step(cfg, mesh, opt)
        t = toks(b=4)
        losses = []
        for _ in range(8):
            params, opt_state, m = step(params, opt_state, t)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] - 0.2, losses

    @pytest.mark.slow  # decode-under-tying composition pin; the fast
    # tier keeps test_no_lm_head_param (tying) and test_generate's
    # greedy e2e pin (decode) — this second pin rides the full tier
    def test_decode_parity(self):
        from akka_allreduce_tpu.models.generate import (decode_step,
                                                        init_kv_cache)
        params = init_transformer(jax.random.key(4), TCFG)
        t = toks(b=2, t=10, seed=5)
        full = transformer_apply(params, t, TCFG)
        cache = init_kv_cache(TCFG, batch=2)
        outs = []
        for i in range(t.shape[1]):
            cache, logits = jax.jit(decode_step, static_argnames="cfg")(
                params, cache, t[:, i], TCFG)
            outs.append(logits)
        np.testing.assert_allclose(np.asarray(jnp.stack(outs, axis=1)),
                                   np.asarray(full), atol=2e-4, rtol=2e-3)
