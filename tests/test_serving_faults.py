"""Serving-plane fault tolerance (ISSUE 5): every failure path driven
by a scheduled fault, never by hoping.

THE acceptance property, per fault class: the engine completes the
round without the missing contribution. A hung dispatch trips the
watchdog and fails only the in-flight requests (rebuilt state, warmed
programs, zero recompiles); a raising dispatch takes the same path; a
NaN-poisoned decode fails the poisoned request through the on-device
finite guard; a preemption drains to resumable snapshots a fresh engine
restores with bitwise parity. In EVERY case each submitted request ends
with exactly one terminal record, and every request that completes at
all completes with tokens bitwise identical to the fault-free run —
retries and restores are invisible in the output, visible only in the
ledger (retries/evictions/dead-letter/watchdog counters, which this
file pins exactly).

Model shapes mirror the chaos selfcheck (tiny, unique to this file);
the module-scope baselines double as program warmup so watchdog'd runs
never time a cold XLA compile (the warm-before-you-arm rule,
OPERATIONS.md "Watchdog trips")."""

import dataclasses

import jax
import numpy as np
import pytest

from akka_allreduce_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from akka_allreduce_tpu.runtime.faults import (
    FaultPlan,
    FaultPoint,
    InjectedFault,
    maybe_fail,
)
from akka_allreduce_tpu.serving import (
    EngineConfig,
    Request,
    RequestScheduler,
    RetryPolicy,
    SchedulerConfig,
    ServingEngine,
    ServingMetrics,
    serve_loop,
)

CFG = TransformerConfig(vocab_size=67, d_model=32, n_heads=2,
                        n_layers=2, d_ff=64, max_seq=48)
SLOTS = 3
WATCHDOG_S = 0.15  # dispatch bound; injected hangs sleep 4x this


@pytest.fixture(scope="module")
def params():
    return init_transformer(jax.random.key(0), CFG)


def make_requests(n=6, budget=6, seed=3, eos_every=2, deadline=None):
    """Fresh Request objects every call: requests are mutated in flight
    (attempts, backoff arrival) and runs must not share that state."""
    rng = np.random.default_rng(seed)
    return [Request(
        rid=rid,
        prompt=tuple(int(x) for x in rng.integers(
            0, CFG.vocab_size, size=(3, 5)[rid % 2])),
        max_new_tokens=budget,
        eos_token=3 if eos_every and rid % eos_every == 0 else None,
        deadline=deadline,
        submitted_at=0.0) for rid in range(n)]


def build(params, s=1, watchdog=WATCHDOG_S, max_attempts=3,
          base_delay=0.0, policy="fifo", clock=None, sleep=None,
          metrics=None, **scfg_kw):
    ecfg = EngineConfig(num_slots=SLOTS, decode_steps=s,
                        watchdog_timeout_s=watchdog)
    engine = ServingEngine(
        params, CFG, ecfg, metrics=metrics,
        **({"clock": clock} if clock is not None else {}))
    kw = {}
    if clock is not None:
        kw["clock"] = clock
    if sleep is not None:
        kw["sleep"] = sleep
    sched = RequestScheduler(
        SchedulerConfig(policy=policy,
                        retry=RetryPolicy(max_attempts=max_attempts,
                                          base_delay=base_delay),
                        **scfg_kw),
        num_slots=SLOTS, **kw)
    return engine, sched


def run_to_completion(params, engine, sched, reqs, metrics=None,
                      plan=None):
    """serve_loop plus the preemption handoff: a drained run restores
    its snapshots into a fresh engine (same config) and finishes the
    queue — the production restart choreography the drain tests pin."""
    for r in reqs:
        sched.submit(r)
    import contextlib
    ctx = plan.armed() if plan is not None else contextlib.nullcontext()
    with ctx:
        results = serve_loop(engine, sched, metrics=metrics,
                             max_dispatches=2000)
    while engine.drained or sched.unfinished:
        fresh = ServingEngine(params, CFG, engine.ecfg,
                              metrics=metrics)
        for rr in engine.drained:
            sched.bind(rr.req, fresh.restore(rr))
        results.update(serve_loop(fresh, sched, metrics=metrics,
                                  max_dispatches=2000))
        engine = fresh
    return results, engine


@pytest.fixture(scope="module")
def baselines(params):
    """Fault-free truth per decode_steps — and the program warmup that
    keeps watchdog'd runs from timing cold compiles."""
    out = {}
    for s in (1, 4):
        engine, sched = build(params, s=s, watchdog=None)
        out[s], _ = run_to_completion(params, engine, sched,
                                      make_requests())
    return out


def point_for(kind, s):
    if kind == "hang":
        return FaultPoint("engine.dispatch", "hang", hit=2,
                          duration_s=4 * WATCHDOG_S)
    if kind == "raise":
        return FaultPoint("engine.dispatch", "raise", hit=2)
    if kind == "nan":
        return FaultPoint("engine.logits", "nan", hit=2, slot=1)
    # preempt while work is genuinely in flight: at S=1 the third loop
    # tick has every first-wave lane mid-decode; at S=4 the second tick
    # lands between blocks with 4 of 6 budgeted tokens emitted
    return FaultPoint("serve.loop", "preempt", hit=4 if s == 1 else 2)


class TestFaultPlanUnit:
    """The harness itself: deterministic, scoped, ledgered."""

    def test_unarmed_is_noop(self):
        assert maybe_fail("engine.dispatch") is None

    def test_hit_window_and_times(self):
        naps = []
        plan = FaultPlan([FaultPoint("site", "hang", hit=2, times=2,
                                     duration_s=0.5)],
                         sleep=naps.append)
        with plan.armed():
            assert maybe_fail("site") is None          # hit 1
            assert maybe_fail("site").kind == "hang"   # hit 2 fires
            assert maybe_fail("site").kind == "hang"   # hit 3 fires
            assert maybe_fail("site") is None          # window closed
        assert naps == [0.5, 0.5]
        assert plan.fired == [("site", "hang", 2), ("site", "hang", 3)]

    def test_raise_kind_raises(self):
        plan = FaultPlan([FaultPoint("s", "raise")])
        with plan.armed():
            with pytest.raises(InjectedFault, match="'s'"):
                maybe_fail("s")
        assert plan.fired == [("s", "raise", 1)]

    def test_plans_do_not_nest_and_disarm(self):
        plan = FaultPlan([FaultPoint("s", "preempt")])
        with plan.armed():
            with pytest.raises(RuntimeError, match="already armed"):
                with FaultPlan([]).armed():
                    pass
        assert maybe_fail("s") is None  # disarmed on exit

    def test_wrap_clock_skew(self):
        plan = FaultPlan([FaultPoint("scheduler.clock", "skew", hit=3,
                                     duration_s=100.0)])
        t = [0.0]
        clock = plan.wrap_clock(lambda: t[0])
        with plan.armed():
            assert clock() == 0.0
            assert clock() == 0.0
            assert clock() == 100.0  # third read fires the jump
            assert clock() == 100.0  # and it sticks
        assert ("scheduler.clock", "skew", 3) in plan.fired

    def test_validation(self):
        with pytest.raises(ValueError, match="kind"):
            FaultPoint("s", "explode")
        with pytest.raises(ValueError, match="hit"):
            FaultPoint("s", "hang", hit=0)


class TestFaultMatrix:
    """The ISSUE 5 matrix: (hang, raise, nan, preempt) x (fifo,
    deadline) x decode_steps in {1, 4}. Every request's final tokens
    and reason are bitwise the fault-free run's, and the failure ledger
    reconciles exactly."""

    @pytest.mark.parametrize("kind", ["hang", "raise", "nan", "preempt"])
    @pytest.mark.parametrize("policy", ["fifo", "deadline"])
    @pytest.mark.parametrize("s", [1, 4])
    def test_matrix(self, params, baselines, race_probe, kind, policy,
                    s):
        reqs = make_requests()
        plan = FaultPlan([point_for(kind, s)])
        metrics = ServingMetrics()
        engine, sched = build(params, s=s, policy=policy,
                              metrics=metrics)
        results, engine = run_to_completion(params, engine, sched, reqs,
                                            metrics=metrics, plan=plan)
        assert len(plan.fired) == 1, plan.fired
        # parity: faults are invisible in every request's output
        assert set(results) == set(baselines[s])
        for rid, (toks, reason) in baselines[s].items():
            assert list(results[rid][0]) == list(toks), f"rid={rid}"
            assert results[rid][1] == reason, f"rid={rid}"
        # the ledger, exactly
        assert metrics.fault_survived == 1
        assert metrics.dead_letter_total == 0
        if kind == "hang":
            assert engine.watchdog_trips == 1
            assert metrics.watchdog_trips_total == 1
            assert metrics.retries_total == SLOTS  # all in-flight
            assert metrics.requests_failed == SLOTS
        elif kind == "raise":
            assert metrics.watchdog_trips_total == 0
            assert metrics.retries_total == SLOTS
        elif kind == "nan":
            assert metrics.retries_total == 1  # the poisoned lane only
            assert metrics.requests_failed == 1
        else:  # preempt
            assert metrics.retries_total == 0
            assert metrics.requests_failed == 0


class TestWatchdogRecovery:
    def test_recovery_compiles_nothing(self, params, baselines):
        """The rebuilt-state dispatch contract at runtime (the lint
        half is the engine_recovery catalog entry): with programs
        warmed, the ENTIRE faulted run — trip, rebuild, retries, churn
        — compiles zero programs."""
        from akka_allreduce_tpu.analysis.recompile import no_recompiles
        plan = FaultPlan([point_for("hang", 1)])
        engine, sched = build(params, s=1, metrics=None)
        with no_recompiles("watchdog recovery at warmed shapes"):
            results, engine = run_to_completion(
                params, engine, sched, make_requests(), plan=plan)
        assert engine.watchdog_trips == 1
        for rid, (toks, reason) in baselines[1].items():
            assert list(results[rid][0]) == list(toks)

    def test_discarded_partials_charged_to_waste(self, params,
                                                 baselines):
        """A failed attempt's partial decode is wasted work: moved from
        the decode count to the wasted count, token for token."""
        plan = FaultPlan([point_for("hang", 1)])  # trip at dispatch 2
        metrics = ServingMetrics()
        engine, sched = build(params, s=1, metrics=metrics)
        results, engine = run_to_completion(params, engine, sched,
                                            make_requests(),
                                            metrics=metrics, plan=plan)
        # 3 lanes had emitted exactly 1 token each when dispatch 2 hung
        assert engine.discarded_tokens == SLOTS
        assert metrics.wasted_tokens == SLOTS
        # delivered tokens stay exact despite the discard accounting
        assert metrics.decode_tokens == sum(
            len(t) for t, _ in results.values())

    def test_dead_letter_after_budget(self, params, baselines):
        """Retry exhaustion: a dispatch that fails EVERY time pushes
        each request through max_attempts failures into the dead-letter
        list with a terminal status — and the run still terminates."""
        plan = FaultPlan([FaultPoint("engine.dispatch", "raise",
                                     hit=2, times=10_000)])
        metrics = ServingMetrics()
        engine, sched = build(params, s=1, max_attempts=2,
                              metrics=metrics)
        results, engine = run_to_completion(params, engine, sched,
                                            make_requests(),
                                            metrics=metrics, plan=plan)
        # dispatch 1 succeeded, then nothing ever again: every request
        # burns its 2 attempts and dead-letters
        assert all(r == ([], "dead_letter") for r in results.values())
        assert metrics.dead_letter_total == 6
        assert len(sched.dead_letter) == 6
        assert all(req.attempts == 2 for req, _ in sched.dead_letter)
        # ledger identity: every failed attempt was requeued or
        # dead-lettered, nothing lost, nothing double-counted
        assert metrics.retries_total + metrics.dead_letter_total \
            == metrics.requests_failed == 12


class TestNaNGuard:
    def test_poison_all_lanes_fails_all_retries_all(self, params,
                                                    baselines):
        """slot=None poisons the whole logits batch: every in-flight
        request fails through the finite guard, retries, and still
        lands bitwise on the baseline."""
        plan = FaultPlan([FaultPoint("engine.logits", "nan", hit=2,
                                     slot=None)])
        metrics = ServingMetrics()
        engine, sched = build(params, s=1, metrics=metrics)
        results, _ = run_to_completion(params, engine, sched,
                                       make_requests(),
                                       metrics=metrics, plan=plan)
        assert metrics.requests_failed == SLOTS
        assert metrics.fault_survived == SLOTS  # one per poisoned lane
        for rid, (toks, reason) in baselines[1].items():
            assert list(results[rid][0]) == list(toks)
            assert results[rid][1] == reason


class _TickClock:
    """A clock that advances a fixed dt per READ — deterministic decode
    'wall time' for deadline tests without real sleeping."""

    def __init__(self, dt=0.05):
        self.t = 0.0
        self.dt = dt

    def __call__(self):
        self.t += self.dt
        return self.t

    def sleep(self, dt):
        self.t += dt


class TestDeadlineEnforcement:
    def test_expired_request_evicted_mid_flight(self, params):
        """The deadline field is enforced BETWEEN dispatches: an
        expired request is evicted with its partial decode charged to
        waste, and its slot refills the same iteration."""
        clock = _TickClock(dt=0.05)
        metrics = ServingMetrics(clock=clock)
        engine, sched = build(params, s=1, watchdog=None,
                              policy="deadline", clock=clock,
                              sleep=clock.sleep, metrics=metrics)
        reqs = make_requests(n=4, budget=30, eos_every=0)
        reqs[0] = dataclasses.replace(reqs[0], deadline=1.0)
        for r in reqs[1:]:
            r.deadline = 1e9
        results, engine = run_to_completion(params, engine, sched, reqs,
                                            metrics=metrics)
        assert results[0] == ([], "evicted")
        assert engine.evictions == 1
        assert metrics.evictions_total == 1
        assert metrics.deadline_misses_total == 1
        assert metrics.wasted_tokens > 0  # rid 0's partial decode
        # the freed slot was refilled: everyone else ran to budget
        for rid in (1, 2, 3):
            toks, reason = results[rid]
            assert reason == "max_tokens" and len(toks) == 30

    def test_infeasible_deadline_shed_at_admission(self, params):
        """ISSUE 5 satellite: under the deadline policy with a tpot
        estimate, a request whose deadline cannot fit min_feasible_
        tokens is shed as rejected_infeasible instead of admitted into
        a guaranteed eviction."""
        clock = _TickClock(dt=0.05)
        metrics = ServingMetrics(clock=clock)
        engine, sched = build(params, s=1, watchdog=None,
                              policy="deadline", clock=clock,
                              sleep=clock.sleep, metrics=metrics,
                              tpot_estimate=0.1, min_feasible_tokens=5)
        reqs = make_requests(n=3, budget=6, eos_every=0)
        reqs[0].deadline = 0.2   # < now + 5 * 0.1: unmeetable
        reqs[1].deadline = 1e9
        reqs[2].deadline = 1e9
        results, _ = run_to_completion(params, engine, sched, reqs,
                                       metrics=metrics)
        assert results[0] == ([], "rejected_infeasible")
        assert sched.shed_infeasible == 1
        assert metrics.deadline_misses_total == 1
        assert metrics.evictions_total == 0  # shed, never admitted
        assert len(results[1][0]) == 6 and len(results[2][0]) == 6

    def test_scheduler_infeasible_unit(self):
        t = [100.0]
        sched = RequestScheduler(
            SchedulerConfig(policy="deadline", tpot_estimate=0.1,
                            min_feasible_tokens=5),
            num_slots=2, clock=lambda: t[0])
        bad = Request(rid=0, prompt=(1,), max_new_tokens=8,
                      deadline=100.3)
        ok = Request(rid=1, prompt=(1,), max_new_tokens=8,
                     deadline=101.0)
        sched.submit(bad)
        sched.submit(ok)
        got = sched.pop_ready(100.0)
        assert got is not None and got.rid == 1
        assert sched.drain_dropped() == [(bad, "rejected_infeasible")]
        assert sched.drain_dropped() == []  # drained exactly once


class TestRetryBackoffExact:
    """The satellite's 'retry/backoff accounting is exact' pin, at the
    scheduler unit level with a fake clock."""

    def test_exponential_backoff_and_dead_letter(self):
        t = [1000.0]
        sched = RequestScheduler(
            SchedulerConfig(retry=RetryPolicy(max_attempts=3,
                                              base_delay=0.2)),
            num_slots=1, clock=lambda: t[0])
        req = Request(rid=7, prompt=(1,), max_new_tokens=4)
        assert sched.requeue_failed(req, "watchdog") is True
        assert req.attempts == 1
        assert req.arrival == pytest.approx(1000.0 + 0.2)   # 0.2 * 2^0
        assert sched.requeue_failed(req, "fault") is True
        assert req.attempts == 2
        assert req.arrival == pytest.approx(1000.0 + 0.4)   # 0.2 * 2^1
        assert sched.requeue_failed(req, "nan") is False    # budget out
        assert req.attempts == 3
        assert sched.retries == 2
        assert list(sched.dead_letter) == [(req, "nan")]
        assert sched.drain_dropped() == [(req, "dead_letter")]

    def test_retry_survives_full_queue(self):
        """A retried request re-entering through the future pool must
        NOT be shed by the arrival-time depth check: it already paid
        for its admission, and shedding it would lose it with no
        terminal status (backpressure is an edge policy; a retry is
        not at the edge)."""
        t = [0.0]
        rejected = []
        sched = RequestScheduler(
            SchedulerConfig(max_queue_depth=2,
                            retry=RetryPolicy(max_attempts=3,
                                              base_delay=0.0)),
            num_slots=1, clock=lambda: t[0],
            on_reject=rejected.append)
        for rid in range(2):  # fill the live queue to its depth bound
            sched.submit(Request(rid=rid, prompt=(1,),
                                 max_new_tokens=2))
        failed = Request(rid=9, prompt=(1,), max_new_tokens=2)
        assert sched.requeue_failed(failed, "watchdog") is True
        got = sched.pop_ready(0.0)  # drains arrivals at a FULL queue
        assert rejected == []       # the retry was not shed
        popped = {got.rid, sched.pop_ready(0.0).rid,
                  sched.pop_ready(0.0).rid}
        assert popped == {0, 1, 9}  # everyone eventually pops
        assert sched.drain_dropped() == []

    def test_jitter_is_seeded_and_bounded(self):
        def mk():
            return RequestScheduler(
                SchedulerConfig(retry=RetryPolicy(max_attempts=9,
                                                  base_delay=0.1,
                                                  jitter=0.05),
                                seed=5),
                num_slots=1, clock=lambda: 0.0)

        def delays(s):
            out = []
            for k in range(4):
                r = Request(rid=k, prompt=(1,), max_new_tokens=2)
                s.requeue_failed(r)
                out.append(r.arrival)
            return out

        a, b = delays(mk()), delays(mk())
        assert a == b  # deterministic per seed
        for k, d in enumerate(a):
            base = 0.1 * (2 ** 0)  # every request on its 1st failure
            assert base <= d < base + 0.05, (k, d)


class TestDrainRestore:
    def test_restore_validation(self, params):
        from akka_allreduce_tpu.serving import ResumableRequest
        engine = ServingEngine(params, CFG, EngineConfig(num_slots=1))
        req = Request(rid=0, prompt=(1, 2), max_new_tokens=3,
                      submitted_at=0.0)
        rr = ResumableRequest(req=req, generated=(4, 5, 6), slot=0)
        with pytest.raises(ValueError, match="restore"):
            engine.restore(rr)

    def test_drain_snapshots_and_restore_parity(self, params,
                                                baselines):
        """Drain mid-decode, restore into a fresh engine, and the
        continued streams are bitwise the uninterrupted ones — plus the
        snapshots really carry the partial progress."""
        plan = FaultPlan([point_for("preempt", 1)])
        engine, sched = build(params, s=1)
        reqs = make_requests()
        for r in reqs:
            sched.submit(r)
        with plan.armed():
            early = serve_loop(engine, sched, max_dispatches=2000)
        assert engine.draining
        assert len(engine.drained) == SLOTS  # first wave mid-decode
        assert all(len(rr.generated) >= 1 for rr in engine.drained)
        fresh = ServingEngine(params, CFG, engine.ecfg)
        for rr in engine.drained:
            sched.bind(rr.req, fresh.restore(rr))
        results = dict(early)
        results.update(serve_loop(fresh, sched, max_dispatches=2000))
        for rid, (toks, reason) in baselines[1].items():
            assert list(results[rid][0]) == list(toks), f"rid={rid}"
            assert results[rid][1] == reason


class TestClockSkew:
    def test_skewed_clock_sheds_instead_of_wedging(self, params):
        """Scheduler-clock skew under the deadline policy: a forward
        jump expires everything, and the plane answers with evictions
        and infeasible sheds — terminal statuses for every request,
        never a stall."""
        plan = FaultPlan([FaultPoint("scheduler.clock", "skew",
                                     hit=40, duration_s=1e6)])
        clock = plan.wrap_clock(_TickClock(dt=0.01))
        metrics = ServingMetrics(clock=clock)
        engine, sched = build(params, s=1, watchdog=None,
                              policy="deadline", clock=clock,
                              sleep=lambda dt: None, metrics=metrics,
                              tpot_estimate=0.05)
        reqs = make_requests(n=6, budget=12, eos_every=0)
        for r in reqs:
            r.deadline = 50.0  # generous until the skew lands
        with plan.armed():
            results, _ = run_to_completion(params, engine, sched, reqs,
                                           metrics=metrics)
        assert ("scheduler.clock", "skew", 40) in plan.fired
        assert set(results) == {r.rid for r in reqs}
        statuses = {reason for _, reason in results.values()}
        assert statuses <= {"evicted", "rejected_infeasible",
                            "max_tokens", "eos"}
        # the jump really bit: someone was evicted or shed
        assert metrics.deadline_misses_total >= 1


class TestFaultMetricsSurface:
    def test_summary_carries_the_fault_counters(self):
        m = ServingMetrics()
        m.on_retry(1)
        m.on_evict(2, 3)
        m.on_watchdog_trip()
        m.on_drop(3, "dead_letter")
        m.on_drop(4, "rejected_infeasible")
        m.on_fault_injected(2)
        m.on_fault_survived("watchdog")
        f = m.summary()["faults"]
        assert f == {"retries_total": 1, "evictions_total": 1,
                     "deadline_misses_total": 2,
                     "watchdog_trips_total": 1, "dead_letter_total": 1,
                     "fault_injected": 2, "fault_survived": 1}

    def test_discard_moves_decode_to_wasted(self):
        m = ServingMetrics()
        m.on_block_tokens(1, 0.0, 4)
        assert m.decode_tokens == 4
        m.on_discard(1, 4)
        assert m.decode_tokens == 0 and m.wasted_tokens == 4
        # rate denominator (computed work) is unchanged by the move
        assert m.summary()["wasted_token_rate"] == 1.0


class TestFaultPlanFuzz:
    """Randomized seeds x open-loop load (ISSUE 6 satellite): the same
    treatment tests/test_cluster.py gives the protocol plane, pointed
    at the serving fault plane. Every seed derives a chaos script
    (hang + raise + nan + preempt at seed-staggered hits; later seeds
    add a raise BURST long enough to exhaust retry budgets) and an
    open-loop arrival schedule, and EVERY seed must reconcile exactly:

    * ``fault_injected == fault_survived`` — each fault the plan fired
      was absorbed by exactly one recovery handler (the dead-letter
      list is downstream bookkeeping of repeated attempts, not an
      unabsorbed fault);
    * ``retries_total + dead_letter_total == requests_failed`` — every
      failed attempt was either requeued or terminally dead-lettered;
    * every submitted request ends with exactly ONE terminal record,
      and every request that completes at all completes bitwise equal
      to the fault-free run.
    """

    @pytest.mark.parametrize("seed", range(6))
    def test_reconciliation_holds_for_every_seed(self, params,
                                                 baselines, seed):
        import time as _time

        rng = np.random.default_rng(seed)
        s = 4 if seed % 2 else 1
        policy = "deadline" if seed >= 3 else "fifo"
        n = 8 + seed % 3

        def fuzz_requests(open_loop):
            r = np.random.default_rng(1000 + seed)
            t0 = _time.monotonic()
            return [Request(
                rid=rid,
                # prompt lengths restricted to the warmed set {3, 5}:
                # the fuzz probes fault handling, not prefill compiles
                prompt=tuple(int(x) for x in r.integers(
                    0, CFG.vocab_size, size=(3, 5)[rid % 2])),
                max_new_tokens=int(r.integers(6, 9)),
                eos_token=3 if rid % 3 == 0 else None,
                arrival=(t0 + 0.005 * rid) if open_loop else 0.0,
                submitted_at=0.0) for rid in range(n)]

        # fault-free truth for THESE requests (closed-loop; greedy
        # tokens are arrival-independent by the engine parity contract)
        engine, sched = build(params, s=s, watchdog=None, policy=policy)
        truth, _ = run_to_completion(params, engine, sched,
                                     fuzz_requests(open_loop=False))

        # the chaos script's fault mix at seed-derived but TIGHT
        # staggering (chaos()'s wider preempt offset can outlive a
        # short S=4 run): hang -> raise -> nan -> preempt, each a few
        # hits after the previous one's recovery
        import random as _random
        prng = _random.Random(seed)
        h = prng.randint(1, 2)
        if seed >= 4:
            # a raise BURST instead of a single raise, against a
            # 2-attempt budget: four consecutive dying dispatches at
            # full occupancy spread up to 12 failed attempts over
            # n <= 10 requests, so by pigeonhole somebody spends the
            # budget and dead-letters — while most requests survive to
            # carry the later nan/preempt. The burst must land before
            # the preempt: run_to_completion's restore loop runs
            # UNARMED (the production restart is a fresh process)
            nn = h + 6 + prng.randint(0, 1)
            points = [
                FaultPoint("engine.dispatch", "hang", hit=h,
                           duration_s=4 * WATCHDOG_S),
                FaultPoint("engine.dispatch", "raise", hit=h + 2,
                           times=4),
                FaultPoint("engine.logits", "nan", hit=nn,
                           slot=prng.randrange(SLOTS)),
                FaultPoint("serve.loop", "preempt", hit=nn + 1),
            ]
        else:
            r_hit = h + prng.randint(2, 3)
            nn = r_hit + prng.randint(2, 3)
            points = [
                FaultPoint("engine.dispatch", "hang", hit=h,
                           duration_s=4 * WATCHDOG_S),
                FaultPoint("engine.dispatch", "raise", hit=r_hit),
                FaultPoint("engine.logits", "nan", hit=nn,
                           slot=prng.randrange(SLOTS)),
                FaultPoint("serve.loop", "preempt", hit=nn + 1),
            ]
        plan = FaultPlan(points, seed=seed)
        metrics = ServingMetrics()
        engine, sched = build(params, s=s, policy=policy,
                              max_attempts=2 if seed >= 4 else 3,
                              metrics=metrics)
        results, _ = run_to_completion(
            params, engine, sched, fuzz_requests(open_loop=True),
            metrics=metrics, plan=plan)
        metrics.on_fault_injected(len(plan.fired))

        fired_kinds = {k for _site, k, _hit in plan.fired}
        assert {"hang", "raise", "nan", "preempt"} <= fired_kinds, \
            f"seed {seed}: not every fault fired: {sorted(plan.fired)}"
        # reconciliation, exact, every seed
        assert metrics.fault_injected == metrics.fault_survived, \
            f"seed {seed}: injected {metrics.fault_injected} != " \
            f"survived {metrics.fault_survived}"
        assert metrics.retries_total + metrics.dead_letter_total \
            == metrics.requests_failed, \
            f"seed {seed}: retry ledger off"
        # one terminal record per submitted request
        assert set(results) == set(range(n)), f"seed {seed}"
        for rid, (toks, reason) in results.items():
            if reason == "dead_letter":
                assert toks == [] and seed >= 4
                continue
            want_toks, want_reason = truth[rid]
            assert list(toks) == list(want_toks), \
                f"seed {seed} rid {rid}: chaos diverged from truth"
            assert reason == want_reason
        if seed >= 4:
            assert metrics.dead_letter_total >= 1, \
                f"seed {seed}: the raise burst never exhausted a budget"


class TestDrainPersistence:
    """PR 5 loose end (ISSUE 6 satellite): a preemption drain survives
    a PROCESS boundary — snapshots round-trip through
    runtime/checkpoint.py's atomic JSON sidecar and a next-process
    engine continues them with bitwise parity."""

    def test_round_trip_across_process_boundary(self, params,
                                                baselines, tmp_path):
        from akka_allreduce_tpu.serving import (clear_drained,
                                                load_drained,
                                                persist_drained)

        plan = FaultPlan([point_for("preempt", 1)])
        metrics = ServingMetrics()
        engine, sched = build(params, s=1, metrics=metrics)
        reqs = make_requests()
        for r in reqs:
            sched.submit(r)
        with plan.armed():
            early = serve_loop(engine, sched, metrics=metrics,
                               max_dispatches=2000)
        assert engine.drained, "preempt must leave work in flight"
        n_drained = len(engine.drained)

        path = persist_drained(str(tmp_path), engine.drained,
                               metrics=metrics)
        assert path.endswith("drained_requests.json")
        assert metrics.registry.value(
            "serve_drain_persisted_total") == n_drained

        # "next process": everything reloaded from disk, nothing
        # shared with the drained engine/scheduler
        restored = load_drained(str(tmp_path))
        assert len(restored) == n_drained
        by_rid = {rr.req.rid: rr for rr in engine.drained}
        for rr in restored:
            orig = by_rid[rr.req.rid]
            assert rr.req.prompt == tuple(orig.req.prompt)
            assert rr.req.max_new_tokens == orig.req.max_new_tokens
            assert rr.req.eos_token == orig.req.eos_token
            assert rr.req.attempts == orig.req.attempts
            assert rr.generated == tuple(orig.generated)
            # clock-domain fields deliberately do NOT survive
            assert rr.req.submitted_at is None

        fresh_engine, fresh_sched = build(params, s=1)
        done = set(early)
        drained_rids = set(by_rid)
        for r in make_requests():
            if r.rid not in done and r.rid not in drained_rids:
                fresh_sched.submit(r)
        results = dict(early)
        results.update(serve_loop(fresh_engine, fresh_sched,
                                  max_dispatches=2000,
                                  resume=restored))
        for rid, (toks, reason) in baselines[1].items():
            assert list(results[rid][0]) == list(toks), f"rid={rid}"
            assert results[rid][1] == reason
        # consumed: the sidecar clears so a third run replays nothing
        assert clear_drained(str(tmp_path)) is True
        assert load_drained(str(tmp_path)) == []
        assert clear_drained(str(tmp_path)) is False

    def test_version_guard(self, tmp_path):
        from akka_allreduce_tpu.runtime.checkpoint import save_state_json
        from akka_allreduce_tpu.serving import load_drained
        save_state_json(str(tmp_path), "drained_requests",
                        {"version": 99, "requests": []})
        with pytest.raises(ValueError, match="version"):
            load_drained(str(tmp_path))


class TestTraceCorrelation:
    """ISSUE 6 test-coverage satellite: the per-request correlation id
    (rid on every lifecycle event and span) survives retry and
    eviction — the Perfetto view shows one request track whose slices
    tell the whole story, failures included."""

    def test_rid_survives_retry(self, params, baselines):
        from akka_allreduce_tpu.runtime.tracing import Tracer
        from akka_allreduce_tpu.serving import EngineConfig, ServingEngine

        tracer = Tracer()
        metrics = ServingMetrics(tracer=tracer)
        plan = FaultPlan([point_for("raise", 1)])
        engine = ServingEngine(
            params, CFG,
            EngineConfig(num_slots=SLOTS,
                         watchdog_timeout_s=WATCHDOG_S),
            metrics=metrics, tracer=tracer)
        sched = RequestScheduler(
            SchedulerConfig(retry=RetryPolicy(max_attempts=3,
                                              base_delay=0.0)),
            num_slots=SLOTS)
        reqs = make_requests()
        for r in reqs:
            metrics.on_submit(r.rid)
            sched.submit(r)
        with plan.armed():
            results = serve_loop(engine, sched, metrics=metrics,
                                 max_dispatches=2000)
        failed_rids = [e.fields["rid"] for e in tracer.events
                       if e.kind == "serve_failure"]
        assert failed_rids, "the injected raise failed nobody?"
        rid = failed_rids[0]
        kinds = [e.kind for e in tracer.events
                 if e.fields.get("rid") == rid]
        # the SAME rid threads submit -> admit -> failure -> retry ->
        # re-admit -> complete: correlation intact across the failure
        assert kinds.count("serve_admit") >= 2
        assert "serve_retry" in kinds and "serve_complete" in kinds
        assert results[rid][1] in ("eos", "max_tokens", "stop")
        # and the Perfetto view renders it as one request track with a
        # queued/decode pair per attempt
        doc = tracer.to_chrome_trace()
        tid = 1000 + rid
        slices = [e["name"] for e in doc["traceEvents"]
                  if e.get("tid") == tid and e["ph"] == "X"]
        assert slices.count("request") == 1
        assert slices.count("decode") >= 2

    def test_rid_survives_eviction(self, params, baselines):
        from akka_allreduce_tpu.runtime.tracing import Tracer

        tracer = Tracer()
        clock = _TickClock(dt=0.05)
        metrics = ServingMetrics(tracer=tracer, clock=clock)
        engine, sched = build(params, s=1, watchdog=None,
                              policy="deadline", clock=clock,
                              sleep=clock.sleep, metrics=metrics)
        engine.tracer = tracer
        reqs = make_requests(n=3, budget=20, eos_every=0,
                             deadline=0.4)
        for r in reqs:
            metrics.on_submit(r.rid)
            sched.submit(r)
        serve_loop(engine, sched, metrics=metrics,
                   max_dispatches=2000)
        evicted = [e.fields["rid"] for e in tracer.events
                   if e.kind == "serve_evict"]
        assert evicted, "the 0.4s deadline evicted nobody?"
        rid = evicted[0]
        doc = tracer.to_chrome_trace()
        tid = 1000 + rid
        decode = [e for e in doc["traceEvents"]
                  if e.get("tid") == tid and e.get("name") == "decode"]
        assert decode and decode[-1]["args"]["end"] == "serve_evict"
