"""Engine-plane tests: continuous batching must be invisible to a request.

THE serving contract (ISSUE 2 acceptance): for greedy decode, the tokens
a request gets from the continuous-batching engine are BITWISE identical
to standalone ``generate()`` on that prompt alone — regardless of batch
composition, slot reuse, or admission order. Everything the engine does
for throughput (slot sharing, churn, refill, per-slot positions) must be
unobservable in the output.

Kept lean on compiles: each model/slot-count pair compiles one step
program, each distinct prompt length one prefill program, and reference
``generate()`` calls share (shape, steps) signatures within a config.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from akka_allreduce_tpu.models.generate import generate
from akka_allreduce_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from akka_allreduce_tpu.runtime.tracing import Tracer
from akka_allreduce_tpu.serving import (
    EngineConfig,
    Request,
    RequestScheduler,
    SchedulerConfig,
    ServingEngine,
    ServingMetrics,
    serve_loop,
)

DENSE = TransformerConfig(vocab_size=97, d_model=64, n_heads=4,
                          n_layers=2, d_ff=128, max_seq=32)
LLAMA = TransformerConfig(vocab_size=61, d_model=64, n_heads=4,
                          n_kv_heads=2, n_layers=2, d_ff=128, max_seq=32,
                          rope=True, ffn="swiglu")


def make_requests(cfg, n, steps, seed, plens=(3, 5), eos_every=0):
    rng = np.random.default_rng(seed)
    reqs = []
    for rid in range(n):
        plen = plens[rid % len(plens)]
        reqs.append(Request(
            rid=rid,
            prompt=tuple(int(x) for x in rng.integers(
                0, cfg.vocab_size, size=plen)),
            max_new_tokens=steps,
            eos_token=(3 if eos_every and rid % eos_every == 0
                       else None),
            submitted_at=0.0))
    return reqs


def run_engine(params, cfg, reqs, slots, submit_order=None, **ecfg_kw):
    engine = ServingEngine(params, cfg,
                           EngineConfig(num_slots=slots, **ecfg_kw))
    sched = RequestScheduler(SchedulerConfig(max_queue_depth=len(reqs)),
                             num_slots=slots)
    for i in (submit_order if submit_order is not None
              else range(len(reqs))):
        sched.submit(reqs[i])
    return serve_loop(engine, sched, max_dispatches=2000), engine


def reference(params, cfg, req, kv_dtype=None):
    prompt = jnp.asarray(req.prompt, jnp.int32)[None]
    if req.eos_token is None:
        return np.asarray(generate(params, prompt, cfg,
                                   steps=req.max_new_tokens,
                                   kv_dtype=kv_dtype))[0]
    toks, lengths = generate(params, prompt, cfg,
                             steps=req.max_new_tokens,
                             eos_token=req.eos_token, kv_dtype=kv_dtype)
    return np.asarray(toks)[0][:int(lengths[0])]


def assert_parity(results, params, cfg, reqs, kv_dtype=None):
    for req in reqs:
        want = reference(params, cfg, req, kv_dtype=kv_dtype)
        got = np.asarray(results[req.rid][0], np.int32)
        np.testing.assert_array_equal(
            got, want,
            err_msg=f"rid={req.rid} prompt_len={len(req.prompt)}")


class TestEngineParity:
    """The acceptance property, across >= 3 batch/slot configs."""

    def test_dense_two_slots(self):
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 6, steps=6, seed=11)
        results, _ = run_engine(params, DENSE, reqs, slots=2)
        assert_parity(results, params, DENSE, reqs)

    def test_dense_four_slots_with_churn_and_eos(self):
        """More slots than concurrent work at the tail + EOS finishes at
        staggered times: slots churn through several occupants."""
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 9, steps=7, seed=23, eos_every=2)
        results, engine = run_engine(params, DENSE, reqs, slots=4)
        assert_parity(results, params, DENSE, reqs)
        # churn actually happened: more requests than slots
        assert engine.prefill_dispatches == 9

    def test_llama_family_three_slots(self):
        """GQA + rope + swiglu exercise every decode-math branch the
        engine mirrors from decode_step."""
        params = init_transformer(jax.random.key(2), LLAMA)
        reqs = make_requests(LLAMA, 6, steps=6, seed=37)
        results, _ = run_engine(params, LLAMA, reqs, slots=3)
        assert_parity(results, params, LLAMA, reqs)

    def test_admission_order_invariance(self):
        """The same request set under opposite admission orders gets
        identical per-request tokens: batch composition is provably
        unobservable (shares compiled programs with the 2-slot test)."""
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 6, steps=6, seed=11)
        fwd, _ = run_engine(params, DENSE, reqs, slots=2)
        rev, _ = run_engine(params, DENSE, reqs, slots=2,
                            submit_order=list(reversed(range(6))))
        for req in reqs:
            np.testing.assert_array_equal(
                np.asarray(fwd[req.rid][0]), np.asarray(rev[req.rid][0]))

    def test_int8_kv_engine_matches_int8_generate(self):
        """The quantized serving cache is the quantized decode cache:
        engine int8 tokens equal generate(kv_dtype='int8') bitwise (both
        sides quantize identically; this is parity, not accuracy — the
        accuracy bound lives in test_generate.py::TestQuantizedKV)."""
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 4, steps=6, seed=51)
        results, engine = run_engine(params, DENSE, reqs, slots=2,
                                     kv_dtype="int8")
        assert_parity(results, params, DENSE, reqs, kv_dtype="int8")
        # and the cache really is int8: 4x smaller values than f32
        assert engine._state["k"].dtype == jnp.int8


class TestBucketedPrefill:
    def test_bucketed_tokens_match_exact(self):
        """Bucketed prefill (prompts padded to one bucket length, logits
        gathered at the true last position) emits the same greedy tokens
        as exact-length prefill. Token-level, not a bitwise-logit claim:
        padding changes reduction lengths at the ulp level (the module
        docstring's reason exact mode is the parity default)."""
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 6, steps=6, seed=11)
        exact, _ = run_engine(params, DENSE, reqs, slots=2)
        bucketed, engine = run_engine(params, DENSE, reqs, slots=2,
                                      prefill_buckets=(8,))
        for req in reqs:
            np.testing.assert_array_equal(
                np.asarray(exact[req.rid][0]),
                np.asarray(bucketed[req.rid][0]))

    def test_prompt_over_largest_bucket_rejected(self):
        params = init_transformer(jax.random.key(0), DENSE)
        engine = ServingEngine(params, DENSE,
                               EngineConfig(num_slots=1,
                                            prefill_buckets=(4,)))
        with pytest.raises(ValueError, match="bucket"):
            engine.admit(Request(rid=0, prompt=tuple(range(6)),
                                 max_new_tokens=2, submitted_at=0.0))


class TestEngineBookkeeping:
    def test_request_budget_validation(self):
        params = init_transformer(jax.random.key(0), DENSE)
        engine = ServingEngine(params, DENSE, EngineConfig(num_slots=1))
        with pytest.raises(ValueError, match="max_seq"):
            engine.admit(Request(rid=0, prompt=tuple(range(30)),
                                 max_new_tokens=10, submitted_at=0.0))
        with pytest.raises(ValueError, match="empty prompt"):
            engine.admit(Request(rid=1, prompt=(), max_new_tokens=2,
                                 submitted_at=0.0))
        with pytest.raises(ValueError, match="out of vocab"):
            engine.admit(Request(rid=2, prompt=(1, 2), max_new_tokens=2,
                                 eos_token=DENSE.vocab_size,
                                 submitted_at=0.0))

    def test_stop_tokens_and_reasons(self):
        """Per-request stop tokens end a request host-side; completion
        reasons are reported per request."""
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 4, steps=6, seed=11)
        base, _ = run_engine(params, DENSE, reqs, slots=2)
        # stop on each request's own second greedy token -> length 2
        stop_reqs = [
            Request(rid=r.rid, prompt=r.prompt, max_new_tokens=6,
                    stop_tokens=(int(np.asarray(base[r.rid][0])[1]),),
                    submitted_at=0.0)
            for r in reqs]
        results, _ = run_engine(params, DENSE, stop_reqs, slots=2)
        for r in stop_reqs:
            toks, reason = results[r.rid]
            assert reason == "stop"
            assert len(toks) == 2
            np.testing.assert_array_equal(
                np.asarray(toks), np.asarray(base[r.rid][0])[:2])

    def test_metrics_and_tracer_wiring(self):
        """TTFT/TPOT/occupancy/queue histograms fill and the tracer sees
        the lifecycle events + spans (the runtime/tracing.py plane)."""
        params = init_transformer(jax.random.key(0), DENSE)
        reqs = make_requests(DENSE, 5, steps=6, seed=11)
        tracer = Tracer()
        engine = ServingEngine(params, DENSE, EngineConfig(num_slots=2),
                               tracer=tracer)
        sched = RequestScheduler(SchedulerConfig(), num_slots=2)
        metrics = ServingMetrics(tracer=tracer)
        for r in reqs:
            metrics.on_submit(r.rid)
            sched.submit(r)
        results = serve_loop(engine, sched, metrics=metrics,
                             max_dispatches=2000)
        assert len(results) == 5
        assert metrics.ttft_s.count == 5
        assert metrics.tpot_s.count == 5  # steps > 1 for every request
        assert metrics.requests_completed == 5
        assert metrics.decode_tokens == sum(
            len(t) for t, _ in results.values())
        assert metrics.decode_tokens_per_s > 0
        occ = metrics.slot_occupancy
        assert occ.count == engine.decode_dispatches
        assert 0 < occ.percentile(50) <= 1.0
        assert tracer.counters["serve_prefill"] == 5
        assert tracer.counters["serve_step"] == engine.decode_dispatches
        assert tracer.counters["serve_complete"] == 5
        assert tracer.counters["serve_first_token"] == 5
        summary = metrics.summary()
        assert summary["requests"]["completed"] == 5
        assert summary["ttft_ms"]["p99"] >= summary["ttft_ms"]["p50"]

    def test_threshold_gate_defers_thin_batches(self):
        """th_step=1.0 (the full-batch barrier baseline) with staggered
        arrivals: the loop waits for quorum while more work is due, and
        still drains a thin tail (liveness)."""
        params = init_transformer(jax.random.key(0), DENSE)

        class FakeClock:
            t = 0.0

            def __call__(self):
                return self.t

            def sleep(self, dt):
                FakeClock.t += dt

        FakeClock.t = 0.0
        clock = FakeClock()
        reqs = make_requests(DENSE, 3, steps=4, seed=11)
        for i, r in enumerate(reqs):
            r.arrival = float(i)  # one new arrival per "second"
        engine = ServingEngine(params, DENSE, EngineConfig(num_slots=2))
        sched = RequestScheduler(
            SchedulerConfig(th_step=1.0), num_slots=2,
            clock=clock, sleep=clock.sleep)
        for r in reqs:
            sched.submit(r)
        results = serve_loop(engine, sched, max_dispatches=2000)
        assert len(results) == 3  # the odd tail request still finished
        assert_parity(results, params, DENSE, reqs)


class TestNoRecompileContract:
    """ISSUE 3 satellite: the engine's "slot churn and refill never
    recompile" claim, asserted with the compile-counting guard
    (analysis/recompile.py) instead of inferred from dispatch counts.

    Uses a config with shapes unique to this test so the module-level
    ``_engine_step``/``_engine_prefill`` jit caches are cold regardless
    of which tests ran earlier in the process."""

    # d_model/vocab chosen to collide with no other config in the suite
    COLD = TransformerConfig(vocab_size=89, d_model=48, n_heads=4,
                             n_layers=2, d_ff=96, max_seq=32)

    def _run(self, params, n_requests):
        reqs = make_requests(self.COLD, n_requests, steps=5, seed=7)
        return run_engine(params, self.COLD, reqs, slots=2)

    def test_warmup_compiles_exactly_then_churn_compiles_nothing(self):
        from akka_allreduce_tpu.analysis.recompile import (CompileLog,
                                                           no_recompiles)
        params = init_transformer(jax.random.key(5), self.COLD)
        with CompileLog() as warm:
            results, engine = self._run(params, 4)
        assert len(results) == 4
        # exactly one decode program and one prefill program per
        # distinct prompt length (make_requests uses plens=(3, 5)) —
        # the compiled-program budget the engine's docstring promises
        engine_programs = [n for n in warm.compiled if "engine" in n]
        assert sorted(engine_programs) == [
            "_engine_prefill", "_engine_prefill", "_engine_step"], \
            warm.compiled
        assert engine.prefill_shapes == {(3, False), (5, False)}
        # churn + refill at warmed shapes: a FRESH engine (new slot
        # state, same shapes) over more requests than slots — zero new
        # programs, by contract
        with no_recompiles("engine churn/refill"):
            results, engine = self._run(params, 8)
        assert len(results) == 8
        assert engine.prefill_dispatches == 8  # churn actually happened

    def test_bucketed_prefill_bounds_programs_under_guard(self):
        """prefill_buckets: requests at 4 distinct lengths but ONE
        bucket — warmup compiles one prefill program, then every other
        length rides it (zero compiles), the program-count bound the
        knob exists to buy."""
        from akka_allreduce_tpu.analysis.recompile import (CompileLog,
                                                           no_recompiles)
        # its OWN unique config: sharing COLD would warm the module-
        # level _engine_step cache for the other test and make the
        # pair order-dependent
        cfg = TransformerConfig(vocab_size=83, d_model=48, n_heads=4,
                                n_layers=2, d_ff=96, max_seq=32)
        params = init_transformer(jax.random.key(6), cfg)
        engine = ServingEngine(params, cfg,
                               EngineConfig(num_slots=2,
                                            prefill_buckets=(8,)))
        sched = RequestScheduler(SchedulerConfig(max_queue_depth=16),
                                 num_slots=2)
        reqs = make_requests(cfg, 2, steps=4, seed=9, plens=(4,))
        for r in reqs:
            sched.submit(r)
        with CompileLog() as warm:
            serve_loop(engine, sched, max_dispatches=500)
        assert warm.compiled.count("_engine_prefill") == 1, warm.compiled
        sched2 = RequestScheduler(SchedulerConfig(max_queue_depth=16),
                                  num_slots=2)
        more = make_requests(cfg, 6, steps=4, seed=10,
                             plens=(2, 3, 5, 6))
        for r in more:
            sched2.submit(r)
        with no_recompiles("bucketed prefill at new lengths"):
            results = serve_loop(engine, sched2, max_dispatches=500)
        assert len(results) == 6
        assert engine.prefill_shapes == {(8, True)}
