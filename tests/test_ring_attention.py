"""Ring attention (sequence parallelism) vs the single-device oracle.

Forward AND backward parity — ppermute+scan must autodiff to the same
gradients the dense attention produces.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from akka_allreduce_tpu.parallel.mesh import single_axis_mesh
from akka_allreduce_tpu.parallel.ring_attention import (
    local_causal_attention,
    ring_attention,
)

N = 8
B, T, H, D = 2, 32, 2, 8  # global sequence T, split over N ranks


@pytest.fixture(scope="module")
def mesh():
    return single_axis_mesh("sp")


def rand_qkv(seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(size=(B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


def shard_seq(x):
    """(B, T, ...) -> (N, B, T/N, ...) stacked for P('sp') sharding."""
    return jnp.stack(jnp.split(x, N, axis=1))


def unshard_seq(x):
    return jnp.concatenate(list(x), axis=1)


class TestForwardParity:
    def test_causal_matches_oracle(self, mesh):
        q, k, v = rand_qkv()
        oracle = local_causal_attention(q, k, v)

        @partial(jax.shard_map, mesh=mesh, in_specs=P("sp"),
                 out_specs=P("sp"))
        def run(qs, ks, vs):
            return ring_attention(qs[0], ks[0], vs[0], "sp", causal=True)[None]

        out = unshard_seq(run(shard_seq(q), shard_seq(k), shard_seq(v)))
        np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.slow
    def test_non_causal_matches_full_softmax(self, mesh):
        q, k, v = rand_qkv(1)
        scale = D ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        p = jax.nn.softmax(scores, axis=-1)
        oracle = jnp.einsum("bhqk,bkhd->bqhd", p, v)

        @partial(jax.shard_map, mesh=mesh, in_specs=P("sp"),
                 out_specs=P("sp"))
        def run(qs, ks, vs):
            return ring_attention(qs[0], ks[0], vs[0], "sp",
                                  causal=False)[None]

        out = unshard_seq(run(shard_seq(q), shard_seq(k), shard_seq(v)))
        np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.slow
class TestBackwardParity:
    def test_gradients_match_oracle(self, mesh):
        q, k, v = rand_qkv(2)
        tgt = jnp.asarray(
            np.random.default_rng(3).normal(size=(B, T, H, D))
            .astype(np.float32))

        def oracle_loss(q, k, v):
            return jnp.sum((local_causal_attention(q, k, v) - tgt) ** 2)

        og = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)

        @partial(jax.shard_map, mesh=mesh,
                 in_specs=(P("sp"), P("sp"), P("sp"), P("sp")),
                 out_specs=P("sp"))
        def ring_grads(qs, ks, vs, ts):
            def loss(q_, k_, v_):
                out = ring_attention(q_, k_, v_, "sp", causal=True)
                # local partial loss; global loss = psum, but grads wrt
                # local q/k/v need only the local term's cotangents plus
                # cross-rank flows, which ppermute's transpose carries
                return jnp.sum((out - ts[0]) ** 2)

            gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
                qs[0], ks[0], vs[0])
            return jnp.stack([gq, gk, gv])[None]

        out = ring_grads(shard_seq(q), shard_seq(k), shard_seq(v),
                         shard_seq(tgt))
        # out: (N, 3, B, T/N, H, D) -> three full (B, T, H, D) grads
        got = [jnp.concatenate([out[i, j] for i in range(N)], axis=1)
               for j in range(3)]
        for g, o in zip(got, og):
            np.testing.assert_allclose(np.asarray(g), np.asarray(o),
                                       rtol=2e-3, atol=2e-4)


class TestDegenerate:
    def test_single_rank_ring_equals_local(self):
        mesh1 = single_axis_mesh("sp", devices=jax.devices()[:1])
        q, k, v = rand_qkv(4)

        @partial(jax.shard_map, mesh=mesh1, in_specs=P("sp"),
                 out_specs=P("sp"))
        def run(qs, ks, vs):
            return ring_attention(qs[0], ks[0], vs[0], "sp")[None]

        out = run(q[None], k[None], v[None])[0]
        oracle = local_causal_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                                   rtol=2e-4, atol=2e-5)
