"""Expert-parallelism tests: routing math, capacity semantics, and the gold
parity check — MoE dispatched over an 8-rank ep mesh must equal the
all-experts-local computation when capacity binds nothing.

Mirrors the reference's test strategy (SURVEY.md §4): unit-test the pure
math (dispatch/combine tensors here ≈ buffer chunk accounting there), then
prove the distributed path on virtual devices.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from akka_allreduce_tpu.parallel.ep import (
    MoEConfig,
    _top_k_dispatch,
    expert_capacity,
    init_moe_layer,
    moe_ffn,
)
from akka_allreduce_tpu.parallel.mesh import make_device_mesh

D = 16
CFG = MoEConfig(n_experts=8, d_ff=32, capacity_factor=4.0, router_k=2)


def make_x(b, t, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(b, t, D)).astype(np.float32))


class TestCapacity:
    def test_capacity_formula(self):
        # cf * k * N / E = 1.25 * 2 * 64 / 8 = 20
        cfg = MoEConfig(n_experts=8, capacity_factor=1.25, router_k=2)
        assert expert_capacity(cfg, 64) == 20

    def test_capacity_floor_one(self):
        cfg = MoEConfig(n_experts=64, capacity_factor=1.0, router_k=1)
        assert expert_capacity(cfg, 8) == 1


class TestTopKDispatch:
    def test_everything_kept_under_generous_capacity(self):
        probs = jax.nn.softmax(
            jnp.asarray(np.random.default_rng(1).normal(size=(16, 4)),
                        dtype=jnp.float32))
        dispatch, combine, kept, _ = _top_k_dispatch(probs, k=2,
                                                     capacity=16)
        assert float(kept) == 1.0
        # every token occupies exactly k slots
        np.testing.assert_allclose(np.asarray(dispatch.sum((1, 2))), 2.0)
        # combine weights sum to 1 per token (renormalised top-2 gates)
        np.testing.assert_allclose(np.asarray(combine.sum((1, 2))), 1.0,
                                   rtol=1e-5)

    def test_no_slot_collisions(self):
        probs = jax.nn.softmax(
            jnp.asarray(np.random.default_rng(2).normal(size=(32, 4)),
                        dtype=jnp.float32))
        dispatch, _, _, _ = _top_k_dispatch(probs, k=2, capacity=32)
        # each (expert, slot) pair is used by at most one token
        assert float(dispatch.sum(0).max()) <= 1.0

    def test_no_slot_collisions_in_bf16(self):
        # bf16 cumsum saturates past 256; bookkeeping must run in f32
        # regardless of model dtype or slots silently merge
        n = 1024
        probs = jnp.tile(jnp.asarray([[0.9, 0.1]], jnp.bfloat16), (n, 1))
        dispatch, _, kept, _ = _top_k_dispatch(probs, k=1, capacity=n)
        assert dispatch.dtype == jnp.bfloat16
        assert float(dispatch.astype(jnp.float32).sum(0).max()) == 1.0
        assert float(kept) == 1.0

    def test_capacity_one_drops_all_but_first(self):
        # all tokens want expert 0; capacity 1 keeps exactly one first-choice
        probs = jnp.tile(jnp.asarray([[0.97, 0.01, 0.01, 0.01]]), (8, 1))
        dispatch, _, kept, route_frac = _top_k_dispatch(probs, k=1,
                                                        capacity=1)
        assert float(dispatch.sum()) == 1.0
        assert float(kept) == pytest.approx(1 / 8)
        # pre-capacity routing fraction still shows the full imbalance
        np.testing.assert_allclose(np.asarray(route_frac), [1, 0, 0, 0])

    def test_k1_gate_is_router_prob(self):
        probs = jax.nn.softmax(
            jnp.asarray(np.random.default_rng(3).normal(size=(8, 4)),
                        dtype=jnp.float32))
        _, combine, _, _ = _top_k_dispatch(probs, k=1, capacity=8)
        np.testing.assert_allclose(np.asarray(combine.sum((1, 2))),
                                   np.asarray(probs.max(-1)), rtol=1e-5)


@pytest.mark.slow
class TestMoELocal:
    def test_shapes_and_finiteness(self):
        params = init_moe_layer(jax.random.key(0), D, CFG)
        x = make_x(2, 8)
        y, aux = moe_ffn(x, params, CFG, axis_name=None)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()
        assert float(aux["dispatch_fraction"]) == 1.0
        assert np.isfinite(float(aux["aux_loss"]))

    def test_gradients_reach_experts_and_router(self):
        params = init_moe_layer(jax.random.key(0), D, CFG)
        x = make_x(2, 8, seed=4)

        def loss(p):
            y, aux = moe_ffn(x, p, CFG, axis_name=None)
            return jnp.sum(y * y) + aux["aux_loss"]

        g = jax.grad(loss)(params)
        assert float(jnp.abs(g["we1"]).sum()) > 0
        assert float(jnp.abs(g["we2"]).sum()) > 0
        assert float(jnp.abs(g["router"]).sum()) > 0

    def test_tight_capacity_reports_drops(self):
        cfg = MoEConfig(n_experts=2, d_ff=32, capacity_factor=0.25,
                        router_k=1)
        params = init_moe_layer(jax.random.key(1), D, cfg)
        x = make_x(4, 8, seed=5)
        y, aux = moe_ffn(x, params, cfg, axis_name=None)
        assert float(aux["dispatch_fraction"]) < 1.0
        assert np.isfinite(np.asarray(y)).all()

    def test_aux_loss_sees_through_capacity_saturation(self):
        # a saturated expert must NOT read as balanced: the aux loss uses
        # pre-capacity routing fractions, so extreme imbalance scores near
        # coef * E even when capacity clips the dispatch to uniform
        cfg = MoEConfig(n_experts=4, d_ff=32, capacity_factor=0.5,
                        router_k=1, aux_loss_coef=1.0)
        params = init_moe_layer(jax.random.key(2), D, cfg)
        # router forced: every token's top expert is 0 (positive tokens x
        # a router that only scores expert 0)
        params["router"] = jnp.zeros_like(params["router"]
                                          ).at[:, 0].set(10.0)
        x = jnp.abs(make_x(4, 8, seed=6)) + 0.1
        _, aux = moe_ffn(x, params, cfg, axis_name=None)
        balanced_value = cfg.aux_loss_coef  # f=P=1/E -> coef exactly
        assert float(aux["aux_loss"]) > 2.0 * balanced_value


@pytest.mark.slow
class TestMoEDistributedParity:
    """Gold test: 8-way ep dispatch == all-local, when nothing is dropped."""

    @pytest.mark.parametrize("ep,k", [(8, 2), (4, 1), (2, 2)])
    def test_sharded_equals_local(self, ep, k):
        cfg = MoEConfig(n_experts=8, d_ff=32, capacity_factor=8.0,
                        router_k=k)
        params = init_moe_layer(jax.random.key(2), D, cfg)
        b_global, t = 2 * ep, 8
        x = make_x(b_global, t, seed=6)

        y_ref, aux_ref = moe_ffn(x, params, cfg, axis_name=None)
        assert float(aux_ref["dispatch_fraction"]) == 1.0

        mesh = make_device_mesh(axis_names=("ep",), axis_sizes=(ep,),
                                devices=jax.devices()[:ep])
        e_local = cfg.n_experts // ep
        pspec = {"router": P(), "we1": P("ep"), "we2": P("ep")}

        @partial(jax.shard_map, mesh=mesh,
                 in_specs=(P("ep"), pspec), out_specs=(P("ep"), P("ep")),
                 check_vma=False)
        def run(xs, ps):
            assert ps["we1"].shape[0] == e_local
            y, aux = moe_ffn(xs, ps, cfg, axis_name="ep")
            return y, aux["dispatch_fraction"][None]

        y, kept = run(x, params)
        np.testing.assert_allclose(np.asarray(kept), 1.0)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=2e-5, atol=2e-5)

    def test_sharded_grads_match_local(self):
        cfg = MoEConfig(n_experts=4, d_ff=32, capacity_factor=8.0,
                        router_k=2)
        params = init_moe_layer(jax.random.key(3), D, cfg)
        ep = 4
        x = make_x(ep, 4, seed=7)

        def ref_loss(p):
            y, _ = moe_ffn(x, p, cfg, axis_name=None)
            return jnp.sum(y * y)

        g_ref = jax.grad(ref_loss)(params)

        mesh = make_device_mesh(axis_names=("ep",), axis_sizes=(ep,),
                                devices=jax.devices()[:ep])
        pspec = {"router": P(), "we1": P("ep"), "we2": P("ep")}

        @partial(jax.shard_map, mesh=mesh, in_specs=(P("ep"), pspec),
                 out_specs=pspec, check_vma=False)
        def sharded_grad(xs, ps):
            def loss(p):
                y, _ = moe_ffn(xs, p, cfg, axis_name="ep")
                return jnp.sum(y * y)

            g = jax.grad(loss)(ps)
            # expert shards are ep-owned; the replicated router grad needs
            # the cross-ep sum (each rank saw only its tokens)
            g["router"] = jax.lax.psum(g["router"], "ep")
            return g

        g = sharded_grad(x, params)
        for name in ("router", "we1", "we2"):
            np.testing.assert_allclose(np.asarray(g[name]),
                                       np.asarray(g_ref[name]),
                                       rtol=1e-4, atol=1e-5)


class TestScatterDispatch:
    """The index-based (scatter/gather) dispatch must be bit-compatible
    with the einsum formulation: both derive slots from _top_k_assign, so
    outputs, aux metrics, and gradients must agree."""

    def _run(self, dispatch, cfg_kw=None, seed=3):
        kw = {"n_experts": 8, "d_ff": 32, "capacity_factor": 1.0,
              "router_k": 2, "dispatch": dispatch, **(cfg_kw or {})}
        cfg = MoEConfig(**kw)
        params = init_moe_layer(jax.random.key(0), D, cfg)
        x = make_x(2, 16, seed=seed)

        def f(p, x):
            y, aux = moe_ffn(x, p, cfg, axis_name=None)
            return jnp.sum(y ** 2), (y, aux)

        (loss, (y, aux)), grads = jax.value_and_grad(
            f, has_aux=True)(params, x)
        return y, aux, grads

    @pytest.mark.slow
    def test_outputs_and_aux_match_einsum(self):
        y_e, aux_e, _ = self._run("einsum")
        y_s, aux_s, _ = self._run("scatter")
        np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                                   atol=1e-5, rtol=1e-5)
        assert abs(float(aux_s["dispatch_fraction"])
                   - float(aux_e["dispatch_fraction"])) < 1e-6
        assert abs(float(aux_s["aux_loss"])
                   - float(aux_e["aux_loss"])) < 1e-6

    @pytest.mark.slow
    def test_gradients_match_einsum(self):
        _, _, g_e = self._run("einsum")
        _, _, g_s = self._run("scatter")
        paths = [p for p, _ in jax.tree.flatten_with_path(g_e)[0]]
        for pe, ge, gs in zip(paths, jax.tree.leaves(g_e),
                              jax.tree.leaves(g_s)):
            np.testing.assert_allclose(np.asarray(gs), np.asarray(ge),
                                       atol=1e-5, rtol=1e-4,
                                       err_msg=str(pe))

    @pytest.mark.slow  # second pin: dispatch=1.0 path stays fast
    def test_drops_match_under_tight_capacity(self):
        y_e, aux_e, _ = self._run("einsum",
                                  {"capacity_factor": 0.25}, seed=5)
        y_s, aux_s, _ = self._run("scatter",
                                  {"capacity_factor": 0.25}, seed=5)
        assert float(aux_e["dispatch_fraction"]) < 1.0  # drops occurred
        np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                                   atol=1e-5, rtol=1e-5)

    def test_auto_threshold_selects_scatter(self, monkeypatch):
        """'auto' must actually RUN the scatter branch past the size line:
        shrink the threshold so this small shape crosses it and pin the
        output against the forced paths."""
        import akka_allreduce_tpu.parallel.ep as ep_mod
        x = make_x(2, 16, seed=11)
        kw = dict(n_experts=8, d_ff=32, capacity_factor=1.0, router_k=2)
        params = init_moe_layer(jax.random.key(2), D, MoEConfig(**kw))
        # below the line: auto takes the einsum formulation
        y_auto_small, _ = moe_ffn(x, params, MoEConfig(**kw,
                                                       dispatch="auto"),
                                  axis_name=None)
        # force the line below this shape: auto must take scatter and
        # still match (would crash/diverge if the branch mis-selected;
        # the einsum-vs-scatter value parity itself is pinned by
        # test_outputs_and_aux_match_einsum, so no third forced-einsum
        # compile here — fast-tier budget, VERDICT r3 weak #2)
        monkeypatch.setattr(ep_mod, "_EINSUM_DISPATCH_MAX", 1)
        y_auto_big, _ = moe_ffn(x, params, MoEConfig(**kw,
                                                     dispatch="auto"),
                                axis_name=None)
        np.testing.assert_allclose(np.asarray(y_auto_big),
                                   np.asarray(y_auto_small),
                                   atol=1e-5, rtol=1e-5)

    def test_unknown_dispatch_raises(self):
        cfg = MoEConfig(dispatch="nope")
        params = init_moe_layer(jax.random.key(0), D, cfg)
        with pytest.raises(ValueError, match="dispatch"):
            moe_ffn(make_x(1, 4), params, cfg, axis_name=None)

    @pytest.mark.slow
    def test_sharded_scatter_equals_local(self):
        ep = 4
        # generous capacity: sharded capacity is per-RANK (the documented
        # local-token-count rule), so exact sharded==local parity needs
        # headroom — same regime as TestMoESharded's einsum variant
        cfg = MoEConfig(n_experts=8, d_ff=32, capacity_factor=4.0,
                        router_k=2, dispatch="scatter")
        params = init_moe_layer(jax.random.key(1), D, cfg)
        x = make_x(ep, 8, seed=7)
        y_local, _ = moe_ffn(x, params, cfg, axis_name=None)

        mesh = make_device_mesh(axis_names=("ep",), axis_sizes=(ep,),
                                devices=jax.devices()[:ep])
        pspec = {"router": P(), "we1": P("ep"), "we2": P("ep")}

        @partial(jax.shard_map, mesh=mesh, in_specs=(P("ep"), pspec),
                 out_specs=P("ep"))
        def sharded(xs, ps):
            y, _ = moe_ffn(xs, ps, cfg, axis_name="ep")
            return y

        y_sharded = sharded(x, params)
        np.testing.assert_allclose(np.asarray(y_sharded),
                                   np.asarray(y_local),
                                   atol=2e-5, rtol=2e-5)
