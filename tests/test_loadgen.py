"""Stress-plane workload tests (ISSUE 12, serving/loadgen.py).

Pure host tests, fake clocks, no jax: trace determinism, arrival-curve
shape, tenant composition (shared prefixes, slow clients), the
coordinated-omission-safe latency ledger — including THE acceptance
pin: under a scripted stall, the queue-delay-inclusive p99 diverges
from the naive admit-measured p99 by exactly the delay coordinated
omission would hide — the bounded pickup buffer, and knee detection.
"""

import math

import pytest

from akka_allreduce_tpu.serving.loadgen import (
    LatencyLedger,
    PickupBuffer,
    TenantSpec,
    TraceConfig,
    TracedRequest,
    anchor_trace,
    find_knee,
    generate_trace,
    hook_metrics,
    tenant_prefix,
    trace_summary,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestTraceDeterminism:
    def test_same_seed_same_trace(self):
        cfg = TraceConfig(seed=11, n_requests=32)
        a, b = generate_trace(cfg), generate_trace(cfg)
        for ta, tb in zip(a, b):
            assert ta.req.prompt == tb.req.prompt
            assert ta.req.max_new_tokens == tb.req.max_new_tokens
            assert ta.req.arrival == tb.req.arrival
            assert ta.req.seed == tb.req.seed
            assert ta.tenant == tb.tenant

    def test_different_seed_different_trace(self):
        a = generate_trace(TraceConfig(seed=1, n_requests=16))
        b = generate_trace(TraceConfig(seed=2, n_requests=16))
        assert [t.req.prompt for t in a] != [t.req.prompt for t in b]

    def test_rate_only_compresses_poisson_arrivals(self):
        """Under the flat poisson curve the thinning never rejects, so
        two traces at different rates draw IDENTICAL lengths / tenants
        / seeds — a rate sweep varies offered load and nothing else
        (the property measure_fleet_stress leans on)."""
        lo = generate_trace(TraceConfig(seed=3, n_requests=24,
                                        rate=8.0))
        hi = generate_trace(TraceConfig(seed=3, n_requests=24,
                                        rate=128.0))
        for a, b in zip(lo, hi):
            assert a.req.prompt == b.req.prompt
            assert a.req.max_new_tokens == b.req.max_new_tokens
            assert a.req.seed == b.req.seed
            assert a.tenant == b.tenant
            # and the schedule scales by exactly the rate ratio
            assert a.req.arrival == pytest.approx(
                b.req.arrival * 128.0 / 8.0)

    def test_rid_base_and_sorted_arrivals(self):
        tr = generate_trace(TraceConfig(seed=0, n_requests=10),
                            rid_base=100)
        assert [t.req.rid for t in tr] == list(range(100, 110))
        arr = [t.req.arrival for t in tr]
        assert arr == sorted(arr)

    def test_lengths_respect_clamps(self):
        cfg = TraceConfig(seed=5, n_requests=64, max_prompt=10,
                          max_new_tokens=7, min_new_tokens=2)
        for t in generate_trace(cfg):
            assert 1 <= len(t.req.prompt) <= 10
            assert 2 <= t.req.max_new_tokens <= 7


class TestArrivalCurves:
    def _mean_rate(self, cfg):
        tr = generate_trace(cfg)
        span = tr[-1].req.arrival - tr[0].req.arrival
        return (len(tr) - 1) / span

    def test_every_curve_averages_the_configured_rate(self):
        # the sweep's independent variable must stay honest whatever
        # the curve shape (loadgen's _rate_at normalizes for it)
        for arrival in ("poisson", "diurnal", "burst"):
            got = self._mean_rate(TraceConfig(
                seed=9, n_requests=4000, rate=50.0, arrival=arrival))
            assert got == pytest.approx(50.0, rel=0.15), arrival

    def test_burst_clusters_arrivals(self):
        cfg = TraceConfig(seed=4, n_requests=2000, rate=50.0,
                          arrival="burst", burst_period_s=4.0,
                          burst_length_s=0.5, burst_multiplier=8.0)
        tr = generate_trace(cfg)
        in_burst = sum(1 for t in tr
                       if (t.req.arrival % 4.0) < 0.5)
        # duty cycle 1/8 of the period but 8x the rate inside it:
        # roughly half of all arrivals land in the burst window
        assert in_burst / len(tr) > 0.35

    def test_diurnal_modulates(self):
        cfg = TraceConfig(seed=4, n_requests=4000, rate=50.0,
                          arrival="diurnal", diurnal_period_s=2.0,
                          diurnal_amplitude=0.9)
        tr = generate_trace(cfg)
        # peak half-period vs trough half-period of the sine
        peak = sum(1 for t in tr if (t.req.arrival % 2.0) < 1.0)
        trough = len(tr) - peak
        assert peak > trough * 1.5

    def test_unknown_curve_rejected(self):
        with pytest.raises(ValueError, match="unknown arrival curve"):
            TraceConfig(arrival="flashmob")


class TestTenantPopulation:
    def test_prefix_composition(self):
        t = TenantSpec("sys", prefix_len=6, prefix_ratio=1.0, seed=3)
        cfg = TraceConfig(seed=8, n_requests=32, max_prompt=16,
                          tenants=(t,))
        prefix = tenant_prefix(t, cfg.vocab)
        assert len(prefix) == 6
        for tr in generate_trace(cfg):
            assert tr.req.prompt[:6] == prefix
            assert len(tr.req.prompt) > 6  # unique suffix always

    def test_prefix_stable_across_traces(self):
        # the registry-visible bytes must not move between sweeps
        t = TenantSpec("sys", prefix_len=8, seed=5)
        assert tenant_prefix(t, 1024) == tenant_prefix(t, 1024)

    def test_prefix_ratio_zero_means_no_prefix(self):
        t = TenantSpec("sys", prefix_len=6, prefix_ratio=0.0, seed=3)
        cfg = TraceConfig(seed=8, n_requests=32, tenants=(t,))
        prefix = tenant_prefix(t, cfg.vocab)
        assert all(tr.req.prompt[:6] != prefix
                   for tr in generate_trace(cfg))

    def test_weights_shape_the_mix(self):
        cfg = TraceConfig(seed=2, n_requests=600, tenants=(
            TenantSpec("big", weight=3.0, seed=1),
            TenantSpec("small", weight=1.0, seed=2)))
        summ = trace_summary(generate_trace(cfg))
        big = summ["tenants"]["big"]["requests"]
        small = summ["tenants"]["small"]["requests"]
        assert big / (big + small) == pytest.approx(0.75, abs=0.08)

    def test_slow_clients_marked_and_counted(self):
        cfg = TraceConfig(seed=2, n_requests=64, tenants=(
            TenantSpec("slow", slow_client_ratio=1.0,
                       pickup_delay_s=0.25, seed=1),))
        tr = generate_trace(cfg)
        assert all(t.pickup_delay_s == 0.25 for t in tr)
        assert trace_summary(tr)["tenants"]["slow"]["slow_clients"] \
            == 64

    def test_tenant_attribution_travels_on_the_request(self):
        cfg = TraceConfig(seed=2, n_requests=16, tenants=(
            TenantSpec("a", seed=1), TenantSpec("b", seed=2)))
        for t in generate_trace(cfg):
            assert t.req.tenant == t.tenant

    def test_prefix_must_leave_suffix_room(self):
        with pytest.raises(ValueError, match="unique suffix"):
            TraceConfig(max_prompt=8,
                        tenants=(TenantSpec("t", prefix_len=8),))


class TestAnchorTrace:
    def test_anchor_shifts_everything(self):
        cfg = TraceConfig(seed=1, n_requests=8, tenants=(
            TenantSpec("d", deadline_slack_s=2.0),))
        tr = generate_trace(cfg)
        offs = [(t.req.arrival, t.req.deadline) for t in tr]
        anchor_trace(tr, 1000.0)
        for (a0, d0), t in zip(offs, tr):
            assert t.req.arrival == pytest.approx(1000.0 + a0)
            assert t.req.deadline == pytest.approx(1000.0 + d0)
            assert t.req.submitted_at == t.req.arrival


class TestLatencyLedger:
    def test_co_safe_diverges_under_scripted_stall(self):
        """THE acceptance pin: a request scheduled at t=0 that the
        server only admits at t=10 (a stall) and finishes at t=11
        experienced 11 s — the naive admit-measured sample says 1 s.
        The divergence equals the queue delay coordinated omission
        hides."""
        clock = FakeClock()
        led = LatencyLedger(clock=clock)
        for rid in range(10):
            led.on_scheduled(rid, float(rid) * 0.01)
        # healthy phase: rids 0-8 admitted promptly, 100 ms service
        for rid in range(9):
            clock.t = rid * 0.01
            led.on_admit(rid)
            led.on_terminal(rid, "eos", now=clock.t + 0.1)
        # the stall: rid 9 (scheduled at 0.09) admits at t=10
        clock.t = 10.0
        led.on_admit(9)
        led.on_terminal(9, "eos", now=10.1)
        co = led.percentile(led.co_safe_latencies(), 99)
        naive = led.percentile(led.naive_latencies(), 99)
        assert naive == pytest.approx(0.1, abs=1e-9)
        assert co == pytest.approx(10.1 - 0.09, abs=1e-9)
        assert co - naive == pytest.approx(10.0 - 0.09, abs=1e-9)

    def test_agreement_without_a_stall(self):
        clock = FakeClock()
        led = LatencyLedger(clock=clock)
        for rid in range(8):
            led.on_scheduled(rid, float(rid))
            led.on_admit(rid, now=float(rid))
            led.on_terminal(rid, "eos", now=float(rid) + 0.5)
        assert led.co_safe_latencies() == led.naive_latencies()

    def test_first_admit_wins(self):
        # a retry's re-admit must not shrink the naive strawman
        led = LatencyLedger(clock=FakeClock())
        led.on_scheduled(1, 0.0)
        led.on_admit(1, now=1.0)
        led.on_admit(1, now=5.0)
        led.on_terminal(1, "eos", now=6.0)
        assert led.naive_latencies() == [5.0]

    def test_sheds_are_terminal_not_latency(self):
        led = LatencyLedger(clock=FakeClock())
        led.on_scheduled(1, 0.0)
        led.on_scheduled(2, 0.0)
        led.on_terminal(1, "shed_overload", now=1.0)
        led.on_terminal(2, "shed_budget", now=1.0)
        assert led.co_safe_latencies() == []
        assert led.shed_reasons() == {"shed_overload": 1,
                                      "shed_budget": 1}

    def test_unresolved_is_the_open_loop_invariant(self):
        led = LatencyLedger(clock=FakeClock())
        led.on_scheduled(1, 0.0)
        led.on_scheduled(2, 0.0)
        led.on_terminal(1, "eos", now=1.0)
        assert led.unresolved() == [2]
        led.on_terminal(2, "shed_overload", now=1.0)
        assert led.unresolved() == []

    def test_double_terminal_keeps_first(self):
        led = LatencyLedger(clock=FakeClock())
        led.on_scheduled(1, 0.0)
        led.on_terminal(1, "eos", now=1.0)
        led.on_terminal(1, "evicted", now=2.0)
        assert led.terminal[1] == (1.0, "eos")

    def test_summary_shape(self):
        led = LatencyLedger(clock=FakeClock())
        led.on_scheduled(1, 0.0)
        led.on_admit(1, now=0.2)
        led.on_terminal(1, "eos", now=0.5)
        s = led.summary()
        assert s["co_safe_ms"]["p99"] == pytest.approx(500.0)
        assert s["naive_ms"]["p99"] == pytest.approx(300.0)
        assert s["unresolved"] == 0


class _Sink:
    """A minimal metrics duck the ledger wrapper taps."""

    def __init__(self):
        self.calls = []

    def on_admit(self, rid, slot, prompt_len):
        self.calls.append(("admit", rid))

    def on_complete(self, rid, n, reason):
        self.calls.append(("complete", rid))

    def on_drop(self, rid, reason):
        self.calls.append(("drop", rid))

    def on_evict(self, rid, n):
        self.calls.append(("evict", rid))

    def on_reject(self, rid):
        self.calls.append(("reject", rid))

    def on_result(self, rid, reason):
        self.calls.append(("result", rid))

    def custom(self):
        return "passthrough"


class TestHookMetrics:
    def test_hooks_stamp_and_pass_through(self):
        clock = FakeClock()
        led = LatencyLedger(clock=clock)
        sink = _Sink()
        wrapped = hook_metrics(sink, led)
        led.on_scheduled(1, 0.0)
        clock.t = 0.5
        wrapped.on_admit(1, 0, 4)
        clock.t = 1.0
        wrapped.on_complete(1, 8, "eos")
        assert sink.calls == [("admit", 1), ("complete", 1)]
        assert led.admitted[1] == 0.5
        assert led.terminal[1] == (1.0, "eos")
        assert wrapped.custom() == "passthrough"

    def test_drop_evict_reject_are_terminal(self):
        led = LatencyLedger(clock=FakeClock())
        wrapped = hook_metrics(_Sink(), led)
        wrapped.on_drop(1, "shed_budget")
        wrapped.on_evict(2, 3)
        wrapped.on_reject(3)
        assert led.terminal[1][1] == "shed_budget"
        assert led.terminal[2][1] == "evicted"
        assert led.terminal[3][1] == "rejected"

    def test_pickup_rides_completion_idempotently(self):
        clock = FakeClock()
        led = LatencyLedger(clock=clock)
        buf = PickupBuffer(capacity=4, clock=clock)
        wrapped = hook_metrics(_Sink(), led, buf, {1: 0.5})
        wrapped.on_complete(1, 8, "eos")
        wrapped.on_result(1, "eos")  # fleet echo of the same terminal
        assert buf.waiting == 1

    def test_fleet_replica_sinks_wrapped_in_place(self):
        class Fleet:
            def __init__(self):
                self.replicas = [_Sink(), _Sink()]

            def on_result(self, rid, reason):
                pass

        led = LatencyLedger(clock=FakeClock())
        fleet = Fleet()
        hook_metrics(fleet, led)
        fleet.replicas[0].on_admit(7, 0, 4)
        assert 7 in led.admitted


class TestPickupBuffer:
    def test_blocks_at_capacity_and_releases_on_time(self):
        clock = FakeClock()
        buf = PickupBuffer(capacity=2, clock=clock)
        buf.on_finish(1, 0.5)
        buf.on_finish(2, 0.5)
        assert not buf.admit_ok()
        assert buf.blocked_polls == 1
        clock.t = 0.6
        assert buf.admit_ok()          # both picked up
        assert buf.picked_up == 2
        assert buf.waiting == 0

    def test_fast_clients_never_buffer(self):
        buf = PickupBuffer(capacity=1, clock=FakeClock())
        buf.on_finish(1, 0.0)
        assert buf.waiting == 0
        assert buf.admit_ok()

    def test_composes_with_scheduler_admit_gate(self):
        from akka_allreduce_tpu.serving.scheduler import (
            Request, RequestScheduler, SchedulerConfig)

        clock = FakeClock()
        buf = PickupBuffer(capacity=1, clock=clock)
        sched = RequestScheduler(SchedulerConfig(), num_slots=2,
                                 clock=clock,
                                 admit_gate=buf.admit_ok)
        sched.submit(Request(rid=1, prompt=(1, 2), max_new_tokens=4,
                             arrival=0.0))
        buf.on_finish(99, 1.0)        # a slow reader holds the buffer
        assert sched.pop_ready(0.0) is None
        assert sched.blocked_on_client == 1
        assert sched.queue_depth == 1  # held, never lost
        clock.t = 1.5                  # the reader caught up
        got = sched.pop_ready(clock.t)
        assert got is not None and got.rid == 1


class TestFindKnee:
    def test_plateau_detected(self):
        assert find_knee([1, 2, 4, 8], [10, 20, 20.5, 21]) == 1

    def test_growth_through_sweep_returns_last(self):
        assert find_knee([1, 2, 4], [10, 20, 40]) == 2

    def test_collapse_is_also_a_knee(self):
        assert find_knee([1, 2, 4], [10, 20, 5]) == 1

    def test_validation(self):
        with pytest.raises(ValueError, match="equal-length"):
            find_knee([1, 2], [1.0])
        with pytest.raises(ValueError, match="increasing"):
            find_knee([2, 1], [1.0, 2.0])
