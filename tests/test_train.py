"""Full training-step tests: dp x tp x sp composition on the CPU mesh.

The gold test is gradient parity: the sharded step over (dp=2, tp=2, sp=2)
must produce the same synced gradients as an unsharded single-device
computation of the global mean loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from akka_allreduce_tpu.models.train import (
    TrainConfig,
    make_grad_step,
    make_train_state,
    make_train_step,
)
from akka_allreduce_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_apply,
)
from akka_allreduce_tpu.parallel.mesh import MeshSpec, make_device_mesh
from akka_allreduce_tpu.parallel.ring_attention import local_causal_attention

MCFG = TransformerConfig(vocab_size=61, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_seq=64)


def reference_mean_loss(params, tokens, cfg):
    """Unsharded global mean next-token loss (last token has no target)."""
    logits = transformer_apply(params, tokens, cfg,
                               jnp.arange(tokens.shape[1]),
                               local_causal_attention, None)
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, tokens[:, 1:, None], axis=-1)[..., 0]
    return -ll.sum() / ll.size


def make_tokens(b, t, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, MCFG.vocab_size, size=(b, t),
                                    dtype=np.int32))


@pytest.mark.slow
class TestGradParity:
    @pytest.mark.parametrize("spec", [
        MeshSpec(dp=8), MeshSpec(dp=2, tp=2, sp=2), MeshSpec(dp=4, sp=2),
        MeshSpec(dp=4, tp=2),
    ])
    def test_sharded_grads_match_unsharded(self, spec):
        mesh = make_device_mesh(spec)
        cfg = TrainConfig(model=MCFG, bucket_elems=256)
        tokens = make_tokens(b=8, t=32)

        key = jax.random.key(0)
        full_params = init_transformer(key, MCFG, tp=spec.tp)
        ref_grads = jax.grad(
            lambda p: reference_mean_loss(p, tokens, MCFG))(full_params)

        from akka_allreduce_tpu.models.train import param_specs, shard_params
        params = shard_params(full_params, param_specs(MCFG), mesh)
        grad_step = make_grad_step(cfg, mesh)
        grads, metrics = jax.jit(grad_step)(params, tokens)

        ref_loss = reference_mean_loss(full_params, tokens, MCFG)
        np.testing.assert_allclose(float(metrics["loss"]), float(ref_loss),
                                   rtol=1e-4)

        got = jax.tree.leaves(grads)
        want = jax.tree.leaves(ref_grads)
        paths = [p for p, _ in jax.tree.flatten_with_path(ref_grads)[0]]
        for path, g, w in zip(paths, got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=5e-3, atol=1e-5,
                err_msg=f"grad mismatch at {path}")

    def test_min_bucket_count_reports_group_size(self):
        spec = MeshSpec(dp=4, sp=2)
        mesh = make_device_mesh(spec)
        cfg = TrainConfig(model=MCFG, bucket_elems=256)
        params, opt_state, opt = make_train_state(jax.random.key(1), cfg,
                                                  mesh)
        grad_step = make_grad_step(cfg, mesh)
        _, metrics = jax.jit(grad_step)(params, make_tokens(8, 32))
        assert int(metrics["min_bucket_count"]) == 8  # dp*sp contributors


@pytest.mark.slow
class TestTraining:
    def test_loss_decreases_on_copy_task(self):
        """30 steps on a deterministic repeating-token task: the full
        dp x tp x sp step must actually learn."""
        spec = MeshSpec(dp=2, tp=2, sp=2)
        mesh = make_device_mesh(spec)
        cfg = TrainConfig(model=MCFG, learning_rate=3e-3, bucket_elems=256)
        params, opt_state, opt = make_train_state(jax.random.key(2), cfg,
                                                  mesh)
        step = make_train_step(cfg, mesh, opt)
        # periodic sequence -> easily learnable next-token structure
        base = np.tile(np.arange(8, dtype=np.int32), 8)[:32]
        tokens = jnp.asarray(np.tile(base, (8, 1)))
        losses = []
        for _ in range(30):
            params, opt_state, metrics = step(params, opt_state, tokens)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.5, losses

    def test_straggler_masked_step_still_trains(self):
        """valid_buckets masking one bucket: counts report the gap and the
        update still applies (lossy round semantics end-to-end)."""
        spec = MeshSpec(dp=8)
        mesh = make_device_mesh(spec)
        cfg = TrainConfig(model=MCFG, bucket_elems=256)
        params, opt_state, opt = make_train_state(jax.random.key(3), cfg,
                                                  mesh)
        # mask this rank's first bucket on every rank except rank 0:
        # simulate via per-rank masks passed as a sharded argument is
        # overkill here — a uniform mask of bucket 0 on all ranks drops the
        # bucket entirely (count 0 -> grads 0 there, rescale keeps zeros)
        from akka_allreduce_tpu.ops.bucketing import bucketize
        _, spec_b = bucketize(params, cfg.bucket_elems)
        valid = jnp.ones((spec_b.num_buckets,), jnp.int32).at[0].set(0)
        grad_step = make_grad_step(cfg, mesh, valid_buckets=valid)
        grads, metrics = jax.jit(grad_step)(params, make_tokens(8, 32))
        assert int(metrics["min_bucket_count"]) == 0
        # bucket 0 covers the embedding head: its synced grads are zeros
        flat = jax.tree.leaves(grads)[0]  # 'embed' (sorted first... dict)
        # embed is under key 'embed': leaves sorted -> embed first
        assert float(jnp.abs(flat[:4]).max()) == 0.0


class TestCompileStability:
    """ISSUE 3 satellite: the train step's compile-cache stability,
    asserted with the compile-counting guard (analysis/recompile.py).
    One program per shape is the contract that makes --compile-cache
    warm restarts and long runs possible; a step that silently
    recompiles per step would still pass the loss tests."""

    def test_multi_step_run_compiles_once(self):
        """30-step runs already exist above (loss test); here the same
        loop shape is pinned to EXACTLY one compile: the first step
        builds `step`, every later step is a cache hit."""
        from akka_allreduce_tpu.analysis.recompile import (
            CompileLog, no_recompiles)
        spec = MeshSpec(dp=8)
        mesh = make_device_mesh(spec)
        cfg = TrainConfig(model=MCFG, bucket_elems=256)
        params, opt_state, opt = make_train_state(jax.random.key(4),
                                                  cfg, mesh)
        step = make_train_step(cfg, mesh, opt)
        tokens = make_tokens(8, 32, seed=5)
        with CompileLog() as warm:
            params, opt_state, _ = step(params, opt_state, tokens)
        # exactly one step program (first-use dispatch helpers like
        # _multi_slice may ride along in the warmup window)
        assert warm.compiled.count("step") == 1, warm.compiled
        with no_recompiles("warmed train step x4"):
            for _ in range(4):
                params, opt_state, metrics = step(params, opt_state,
                                                  tokens)
        assert np.isfinite(float(metrics["loss"]))

    def test_chunked_multi_step_compiles_once_per_chunk_length(self):
        """make_multi_step (the --steps-per-dispatch path): one compile
        serves every chunk of the same length — dispatch 2 runs under
        the zero-compile guard."""
        from akka_allreduce_tpu.analysis.recompile import (
            CompileLog, no_recompiles)
        from akka_allreduce_tpu.models.train import make_multi_step
        spec = MeshSpec(dp=8)
        mesh = make_device_mesh(spec)
        cfg = TrainConfig(model=MCFG, bucket_elems=256)
        params, opt_state, opt = make_train_state(jax.random.key(5),
                                                  cfg, mesh)
        run_chunk = make_multi_step(cfg, mesh, opt)
        stacked = jnp.stack([make_tokens(8, 32, seed=s)
                             for s in (0, 1)])
        with CompileLog() as warm:
            params, opt_state, _ = run_chunk(params, opt_state, stacked)
        assert warm.compiled.count("run_chunk") == 1, warm.compiled
        stacked2 = jnp.stack([make_tokens(8, 32, seed=s)
                              for s in (2, 3)])
        with no_recompiles("warmed chunked dispatch"):
            params, opt_state, metrics = run_chunk(params, opt_state,
                                                   stacked2)
        assert metrics["loss"].shape == (2,)
