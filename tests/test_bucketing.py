"""Bucketing layer unit tests — the pure-function chunking math, tested
independently exactly as the reference unit-tests its buffer math first
(SURVEY.md §4, §7 build order step 2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from akka_allreduce_tpu.ops.bucketing import (
    bucketize,
    debucketize,
    tree_to_vector,
    vector_to_tree,
    _spec_for,
)


def ragged_tree():
    return {
        "w1": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        "b1": jnp.arange(3, dtype=jnp.float32),
        "nested": {"w2": jnp.ones((5,), dtype=jnp.bfloat16)},
    }


class TestRoundTrip:
    def test_bucketize_round_trips_ragged_tree(self):
        tree = ragged_tree()
        buckets, spec = bucketize(tree, bucket_elems=4)
        assert buckets.shape == (4, 4)  # 14 elems -> 4 buckets of 4
        assert spec.total_size == 14
        assert spec.pad == 2
        back = debucketize(buckets, spec)
        for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
            assert a.dtype == b.dtype
            np.testing.assert_allclose(np.asarray(a, dtype=np.float32),
                                       np.asarray(b, dtype=np.float32))

    def test_padding_is_zero(self):
        tree = {"x": jnp.ones((5,), dtype=jnp.float32)}
        buckets, spec = bucketize(tree, bucket_elems=4)
        assert buckets.shape == (2, 4)
        np.testing.assert_array_equal(np.asarray(buckets)[1, 1:], 0.0)

    def test_exact_fit_no_padding(self):
        tree = {"x": jnp.ones((8,), dtype=jnp.float32)}
        buckets, spec = bucketize(tree, bucket_elems=4)
        assert buckets.shape == (2, 4)
        assert spec.pad == 0

    def test_empty_tree(self):
        buckets, spec = bucketize({}, bucket_elems=4)
        assert buckets.shape == (1, 4)
        assert spec.total_size == 0
        assert debucketize(buckets, spec) == {}

    def test_vector_round_trip_preserves_structure(self):
        tree = ragged_tree()
        vec = tree_to_vector(tree)
        assert vec.shape == (14,)
        spec = _spec_for(tree, bucket_elems=14)
        back = vector_to_tree(vec, spec)
        assert jax.tree.structure(back) == jax.tree.structure(tree)

    def test_bucketize_is_jittable(self):
        tree = ragged_tree()
        _, spec = bucketize(tree, bucket_elems=4)
        jitted = jax.jit(lambda t: bucketize(t, 4)[0])
        buckets = jitted(tree)
        np.testing.assert_allclose(
            np.asarray(debucketize(buckets, spec)["w1"]),
            np.asarray(tree["w1"]))
