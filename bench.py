"""Driver entry point: delegates to the packaged benchmark.

See akka_allreduce_tpu/bench.py for the methodology. Kept at the repo root
as a thin shim because the driver invokes ``python bench.py`` here.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from akka_allreduce_tpu.bench import main  # noqa: E402

if __name__ == "__main__":
    main()
