"""Driver benchmark entry: ALWAYS prints one JSON line to stdout.

Round-1 postmortem (VERDICT.md weak #1): the benchmark initialized this
environment's default TPU backend in-process with no watchdog; the backend
hung for ~35 minutes before failing UNAVAILABLE, the driver timed out, and
no number was captured. The reference's measurement contract is a sink that
always prints (reference: AllreduceWorker.scala:329-343) — so this shim now
guarantees a JSON line lands no matter what the backend does:

  1. attempt the real measurement (akka_allreduce_tpu/bench.py) on the
     default backend in a SUBPROCESS with a hard wall-clock timeout;
  2. on timeout/crash, retry on a forced-CPU platform with a smaller,
     CPU-sized config (still the full bucketize->psum->rescale path);
  3. if every attempt fails, print a JSON line with an "error" field.

Progress goes to stderr throughout; stdout carries single-line JSON rows
with the HEADLINE metric last (the driver's parser takes the last line;
extra rows — e.g. the fused-vs-windowed ``ab_overlap`` A/B under
``AATPU_BENCH_AB_OVERLAP=1`` — ride ahead of it), and only successful
attempts print to stdout.

Env knobs: AATPU_BENCH_TIMEOUT_S (per-attempt wall clock, default 270),
AATPU_BENCH_PLATFORMS (comma list, default "default,cpu"), plus the sizing
knobs documented in akka_allreduce_tpu/bench.py (forwarded verbatim).
"""

import json
import os
import re
import signal
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))

# CPU-sized fallback: 2.5M floats (10 MB) x 40 rounds keeps the attempt in
# tens of seconds on 8 virtual CPU devices while still exercising the full
# device sync path (bucketize -> psum -> rescale -> debucketize).
CPU_FALLBACK_ENV = {
    "AATPU_BENCH_ELEMS": "2500000",
    "AATPU_BENCH_BUCKET_ELEMS": "312500",
    "AATPU_BENCH_R_HI": "40",
    "AATPU_BENCH_R_LO": "10",
    "AATPU_BENCH_REPS": "2",
}


def _ensure_host_device_count(env: dict, n: int) -> None:
    """Merge the device-count flag into XLA_FLAGS: append when absent,
    upgrade when an existing count is smaller (a pre-set '=1' would make
    the 'allreduce' a 1-device no-op and the number meaningless)."""
    flags = env.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    elif int(m.group(1)) < n:
        env["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}")


def _log(msg: str) -> None:
    print(f"[bench-driver] {msg}", file=sys.stderr, flush=True)


def _attempt(platform: str, timeout_s: float
             ) -> "tuple[dict, list] | None":
    """Run one measurement subprocess; return (headline row, extra rows)
    or None when it produced no parseable JSON."""
    env = dict(os.environ)
    env["AATPU_BENCH_PLATFORM"] = platform
    if platform == "cpu":
        for k, v in CPU_FALLBACK_ENV.items():
            env.setdefault(k, v)
        _ensure_host_device_count(env, 8)
    cmd = [sys.executable, "-m", "akka_allreduce_tpu.bench"]
    _log(f"attempt platform={platform} timeout={timeout_s:.0f}s: "
         f"{' '.join(cmd)}")
    # New session so a hung backend init (which ignores SIGTERM while
    # blocked in C) can be killed as a whole process group.
    proc = subprocess.Popen(cmd, cwd=REPO_ROOT, env=env,
                            stdout=subprocess.PIPE, stderr=sys.stderr,
                            text=True, start_new_session=True)
    timed_out = False
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _log(f"attempt platform={platform} timed out; killing process group")
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        # Recover whatever the child already printed: a measurement that
        # emitted its JSON and then hung in backend teardown still counts.
        out, _ = proc.communicate()
        timed_out = True
    if proc.returncode != 0 and not timed_out:
        # still scan for JSON: a child that measured, printed, and then
        # crashed in backend teardown produced a real number
        _log(f"attempt platform={platform} exited rc={proc.returncode}")
    rows = []
    for line in (out or "").strip().splitlines():
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            rows.append(parsed)
    # the headline is the last NON-extra row (the measurement module
    # prints it after the ab_overlap A/B rows under
    # AATPU_BENCH_AB_OVERLAP=1); matching by prefix instead of position
    # keeps a child that timed out mid-A/B — extras printed, headline
    # never reached — from banking an ab_overlap row under the headline
    # slot. Extras ride ahead of it so the harness parser, which takes
    # the last line, still lands on the unchanged headline metric.
    extras = [r for r in rows if r["metric"].startswith("ab_overlap")]
    headline = [r for r in rows if not r["metric"].startswith("ab_overlap")]
    if headline:
        return headline[-1], extras
    if extras:
        # a child killed mid-A/B still banked real measurements (the
        # module prints per-row for exactly this case): pass them
        # through — safe because every caller of this path prints a
        # later row (next platform's headline or the final error row)
        # last, which is the slot the harness parser reads
        for r in extras:
            print(json.dumps(r), flush=True)
    _log(f"attempt platform={platform} printed no headline JSON line"
         + (f" ({len(extras)} ab_overlap extras banked without it)"
            if extras else ""))
    return None


def _fast_probe(timeout_s: float = 90.0) -> bool:
    """Small-matmul probe of the default backend in a budgeted subprocess.

    Round-4 verdict #6: the default-platform attempt burns its full
    watchdog budget (270-420 s) discovering the relay is dead before the
    CPU fallback even starts. A 90 s probe answers the same question at a
    fraction of the budget; an in-process call would hang for hours
    (round-1 postmortem)."""
    code = ("import jax, jax.numpy as jnp; x = jnp.ones((512, 512)); "
            "print('PROBE_OK', float((x @ x).sum()))")
    proc = subprocess.Popen([sys.executable, "-c", code], cwd=REPO_ROOT,
                            stdout=subprocess.PIPE, stderr=sys.stderr,
                            text=True, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.communicate()
        return False
    return "PROBE_OK" in (out or "")


def _last_banked_note() -> str:
    """Cite the last committed on-chip capture so a CPU-fallback round
    still points the reader at real TPU evidence (round-4 verdict #6)."""
    try:
        with open(os.path.join(REPO_ROOT, "perf_tpu.json")) as f:
            perf = json.load(f)
        when = (perf.get("captured_at") or "?")[:19]
        rows = perf.get("headline") or []
        head = next((r for r in rows if "metric" in r), None)
        if head is not None:
            return (f"last banked on-chip capture {when}: "
                    f"{head['metric']}={head.get('value')} "
                    f"{head.get('unit', '')} (perf_tpu.json, committed)")
        return f"last banked on-chip capture {when} (perf_tpu.json)"
    except (OSError, json.JSONDecodeError, KeyError):
        return "no banked on-chip capture found (perf_tpu.json missing)"


def main() -> None:
    # the ab_overlap A/B adds ~10 goodput measurements before the
    # headline, so its default watchdog matches the capture harness's
    # ab_overlap step budget instead of the single-measurement 270 s
    # (an explicit AATPU_BENCH_TIMEOUT_S always wins)
    default_timeout = ("1200" if os.environ.get(
        "AATPU_BENCH_AB_OVERLAP") == "1" else "270")
    timeout_s = float(os.environ.get("AATPU_BENCH_TIMEOUT_S",
                                     default_timeout))
    platforms = os.environ.get("AATPU_BENCH_PLATFORMS", "default,cpu")
    errors = []
    for platform in [p.strip() for p in platforms.split(",") if p.strip()]:
        if platform != "cpu" and not _fast_probe():
            _log(f"fast probe: default backend unreachable in 90s; "
                 f"skipping platform={platform}")
            errors.append(f"{platform}: fast-probe unreachable")
            continue
        attempt = _attempt(platform, timeout_s)
        if attempt is not None:
            result, extras = attempt
            if platform == "cpu":
                # a CPU number is a liveness proof, not the perf claim —
                # point at the banked TPU rows
                result["note"] = (result.get("note", "") +
                                  "; " + _last_banked_note()).lstrip("; ")
            for row in extras:
                print(json.dumps(row), flush=True)
            print(json.dumps(result), flush=True)
            return
        errors.append(f"{platform}: timeout/crash/no-json")
    print(json.dumps({
        "metric": "allreduce_goodput",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
        "error": "; ".join(errors) or "no platforms attempted",
        "note": _last_banked_note(),
    }), flush=True)


if __name__ == "__main__":
    main()
